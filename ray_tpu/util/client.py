"""Ray Client: remote drivers over ``ray://host:port``.

Equivalent of the reference's client mode
(``python/ray/util/client/__init__.py:200``): a thin proxy server runs
next to the cluster; remote Python processes connect with
``ray_tpu.init(address="ray://host:port")`` and use the NORMAL API —
``@remote``, ``put/get/wait``, actors — while every operation executes
in the proxy's driver on the cluster. The client worker duck-types the
``CoreWorker`` surface the public API calls, so no separate client API
exists (the reference generates the same illusion with a gRPC proxy).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any

import cloudpickle

from ..core import serialization
from ..core.ids import JobID, ObjectID, TaskID
from ..core.object_ref import ObjectRef, install_refcount_hooks
from ..core.rpc import EventLoopThread, RetryableRpcClient, RpcServer
from ..core.status import RayTpuError

CLIENT_PREFIX = "ray://"


class ClientServer:
    """Cluster-side proxy: executes client requests as this process's
    driver (it must run in a connected driver process — e.g. the head
    bootstrap or any ``ray_tpu.init()``'d process)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        from ..core.config import get_config
        from ..core.worker import global_worker

        self._worker = global_worker()
        self._io = EventLoopThread("raytpu-client-server")
        self._server = RpcServer(host, port)
        self._server.register_service(self)
        # Per-client object registries: client ref id -> real ObjectRef
        # (dropping a client drops its refs).
        self._refs: dict[str, dict[str, ObjectRef]] = {}
        # Actors each client session OWNS (non-detached, unnamed): killed
        # on disconnect, like handle-GC in a local driver.
        self._client_actors: dict[str, list[bytes]] = {}
        # Session metadata: last_seen (heartbeat reaping), the client's
        # GCS job id (per-client job isolation for observability), and
        # open streaming generators.
        self._sessions: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._timeout = get_config().client_session_timeout_s
        self._stopping = False
        self._io.run_sync(self._server.start())
        self.address = self._server.address
        self._reaper = threading.Thread(
            target=self._reap_loop, name="raytpu-client-reaper", daemon=True)
        self._reaper.start()

    def stop(self) -> None:
        self._stopping = True
        try:
            self._io.run_sync(self._server.stop())
        except Exception:
            pass
        self._io.stop()

    def _reap_loop(self) -> None:
        """Crash cleanup: a client that vanishes without disconnecting
        (killed process, severed network) stops pinging; its session-owned
        actors/refs/streams are reclaimed after the timeout — the
        reference's client reconnect-grace expiry."""
        import time as _time

        while not self._stopping:
            _time.sleep(min(5.0, self._timeout / 3))
            now = _time.monotonic()
            with self._lock:
                dead = [cid for cid, s in self._sessions.items()
                        if now - s["last_seen"] > self._timeout]
            for cid in dead:
                self._cleanup_session(cid, reason="session timeout")

    def _cleanup_session(self, client_id: str, *, reason: str) -> None:
        import logging

        with self._lock:
            self._refs.pop(client_id, None)
            actors = self._client_actors.pop(client_id, [])
            session = self._sessions.pop(client_id, None)
        if session is not None:
            logging.getLogger(__name__).info(
                "client session %s cleaned up (%s): %d actors, %d streams",
                client_id[:12], reason, len(actors),
                len(session.get("streams", {})))
        for state in (session or {}).get("streams", {}).values():
            try:
                state["gen"].close()
            except Exception:
                pass
        for actor_id in actors:
            # Session-owned actors die with the session (the handle-GC
            # semantics a local driver would have given them).
            try:
                self._worker.kill_actor(actor_id)
            except Exception:
                pass
        if session and session.get("job_id") is not None:
            try:
                self._worker._gcs_call("FinishJob", {"job_id": session["job_id"]})
            except Exception:
                pass

    # ------------------------------------------------------------- helpers
    def _client(self, p: dict) -> dict:
        """Touch the session and return its ref registry. Unknown (never
        seen or already-reaped) sessions are REJECTED rather than
        resurrected: a client partitioned past the timeout must fail fast
        with 'session expired', not keep running against destroyed state."""
        import time as _time

        with self._lock:
            session = self._sessions.get(p["client_id"])
            if session is None:
                raise RayTpuError(
                    "client session expired or unknown — reconnect with "
                    "ray_tpu.init(address='ray://...')")
            session["last_seen"] = _time.monotonic()
            return self._refs.setdefault(p["client_id"], {})

    def _resolve(self, p: dict, wire_args: list) -> tuple[tuple, dict]:
        refs = self._client(p)
        args, kwargs = [], {}

        def fix(v):
            if isinstance(v, dict) and v.get("__client_ref__"):
                return refs[v["id"]]
            return v

        for entry in wire_args:
            value = fix(cloudpickle.loads(entry["blob"]))
            if "key" in entry:
                kwargs[entry["key"]] = value
            else:
                args.append(value)
        return tuple(args), kwargs

    def _track(self, p: dict, ref: ObjectRef) -> str:
        rid = uuid.uuid4().hex
        self._client(p)[rid] = ref
        return rid

    # ------------------------------------------------------------ handlers
    async def handle_ClientHello(self, p: dict) -> dict:
        """Session start (the ONLY call that may create a session):
        register a per-client JOB in the GCS (the reference attaches each
        ray:// driver as its own job — job-level observability and
        lifetime isolation), return the ping interval."""
        import time as _time

        from ..core.config import get_config

        reply = self._worker._gcs_call(
            "AddJob", {"driver_address": f"ray-client:{p['client_id'][:12]}"})
        with self._lock:
            self._sessions[p["client_id"]] = {
                "last_seen": _time.monotonic(),
                "job_id": reply.get("job_id"),
                "streams": {},
            }
        return {"job_id": reply.get("job_id"),
                "ping_interval_s": get_config().client_ping_interval_s}

    async def handle_ClientPing(self, p: dict) -> dict:
        self._client(p)  # touches last_seen
        return {}

    def _register_stream(self, p: dict, gen) -> str:
        import asyncio

        sid = uuid.uuid4().hex
        self._client(p)
        with self._lock:
            # next: the index the client may request next; last: cached
            # reply for index next-1 so a RETRIED StreamNext (transport
            # drop after the server consumed the item) replays instead of
            # silently skipping an item. serial: per-stream asyncio lock
            # — a duplicate request racing the still-in-flight original
            # must not pass the cursor check twice and double-consume.
            self._sessions[p["client_id"]]["streams"][sid] = {
                "gen": gen, "next": 0, "last": None,
                "serial": asyncio.Lock()}
        return sid

    async def handle_ClientStreamNext(self, p: dict) -> dict:
        """Idempotent by item index: the client sends the index it wants;
        a duplicate request (RPC retry) replays the cached reply."""
        import asyncio

        self._client(p)
        with self._lock:
            state = self._sessions.get(p["client_id"], {}).get(
                "streams", {}).get(p["stream"])
        if state is None:
            return {"error": cloudpickle.dumps(
                RayTpuError(f"unknown stream {p['stream']!r}"))}
        # Serialize per stream: the cursor/replay check must re-run after
        # any in-flight duplicate finishes, else both pass idx == next
        # and the generator is consumed twice (one item silently lost).
        async with state["serial"]:
            return await self._stream_next_locked(p, state)

    async def _stream_next_locked(self, p: dict, state: dict) -> dict:
        import asyncio

        idx = p.get("index", state["next"])
        if idx == state["next"] - 1 and state["last"] is not None:
            return state["last"]  # retry replay
        if idx != state["next"]:
            return {"error": cloudpickle.dumps(RayTpuError(
                f"stream cursor mismatch: asked {idx}, next {state['next']}"))}

        gen = state["gen"]
        loop = asyncio.get_running_loop()
        # Loop-native wait for availability: no executor thread parks for
        # the whole (possibly unbounded) producer wait — with many idle
        # token streams that would starve every other client RPC.
        fut = loop.create_future()
        if gen._stream.add_item_waiter(gen._cursor, loop, fut):
            try:
                await asyncio.wait_for(fut, p.get("timeout"))
            except asyncio.TimeoutError:
                from ..core.status import GetTimeoutError

                return {"error": cloudpickle.dumps(GetTimeoutError(
                    f"timed out waiting for stream item {idx}"))}

        _END = object()  # StopIteration cannot cross an asyncio Future

        def step():
            try:
                # item (or end) is available: returns without blocking
                return gen._next_sync(30.0)
            except StopIteration:
                return _END

        try:
            ref = await loop.run_in_executor(None, step)
        except Exception as e:
            inner = getattr(e, "_inner", e)
            reply = {"error": cloudpickle.dumps(inner)}
        else:
            reply = {"done": True} if ref is _END else {"ref": self._track(p, ref)}
        with self._lock:
            state["last"] = reply
            state["next"] += 1
        return reply

    async def handle_ClientStreamClose(self, p: dict) -> dict:
        self._client(p)
        with self._lock:
            state = self._sessions.get(p["client_id"], {}).get(
                "streams", {}).pop(p["stream"], None)
        if state is not None:
            state["gen"].close()
        return {}

    async def handle_ClientPut(self, p: dict) -> dict:
        import asyncio

        value = cloudpickle.loads(p["blob"])
        ref = await asyncio.get_running_loop().run_in_executor(
            None, self._worker.put, value)
        return {"ref": self._track(p, ref)}

    async def handle_ClientGet(self, p: dict) -> dict:
        import asyncio

        refs = self._client(p)
        try:
            targets = [refs[r] for r in p["refs"]]
        except KeyError as e:
            return {"error": cloudpickle.dumps(RayTpuError(f"unknown client ref {e}"))}
        loop = asyncio.get_running_loop()
        try:
            values = await loop.run_in_executor(
                None, lambda: self._worker.get(targets, p.get("timeout")))
        except Exception as e:
            # The as_instanceof_cause wrapper class is process-local: ship
            # the inner RayTaskError; the client re-wraps.
            inner = getattr(e, "_inner", e)
            return {"error": cloudpickle.dumps(inner)}
        return {"blob": cloudpickle.dumps(values)}

    async def handle_ClientWait(self, p: dict) -> dict:
        import asyncio

        refs = self._client(p)
        targets = [refs[r] for r in p["refs"]]
        loop = asyncio.get_running_loop()
        ready, not_ready = await loop.run_in_executor(
            None, lambda: self._worker.wait(
                targets, p["num_returns"], p.get("timeout")))
        ready_ids = [p["refs"][targets.index(r)] for r in ready]
        return {"ready": ready_ids,
                "not_ready": [r for r in p["refs"] if r not in ready_ids]}

    async def handle_ClientSubmitTask(self, p: dict) -> dict:
        import asyncio

        fn = cloudpickle.loads(p["fn"])
        args, kwargs = self._resolve(p, p["args"])
        opts = p.get("options") or {}
        loop = asyncio.get_running_loop()
        refs = await loop.run_in_executor(
            None, lambda: self._worker.submit_task(fn, args, kwargs, **opts))
        if not isinstance(refs, list):  # ObjectRefGenerator (streaming)
            return {"stream": self._register_stream(p, refs)}
        return {"refs": [self._track(p, r) for r in refs]}

    async def handle_ClientCreateActor(self, p: dict) -> dict:
        import asyncio

        cls = cloudpickle.loads(p["cls"])
        args, kwargs = self._resolve(p, p["args"])
        opts = p.get("options") or {}
        loop = asyncio.get_running_loop()
        try:
            actor_id = await loop.run_in_executor(
                None, lambda: self._worker.create_actor(cls, args, kwargs, **opts))
        except Exception as e:
            return {"error": cloudpickle.dumps(e)}
        if not opts.get("detached") and not opts.get("name"):
            with self._lock:
                self._client_actors.setdefault(p["client_id"], []).append(actor_id)
        return {"actor_id": actor_id.hex()}

    async def handle_ClientActorCall(self, p: dict) -> dict:
        import asyncio

        args, kwargs = self._resolve(p, p["args"])
        loop = asyncio.get_running_loop()
        refs = await loop.run_in_executor(
            None, lambda: self._worker.submit_actor_task(
                bytes.fromhex(p["actor_id"]), p["method"], args, kwargs,
                num_returns=p.get("num_returns", 1),
                generator_backpressure=p.get("generator_backpressure", 0),
                concurrency_group=p.get("concurrency_group", "")))
        if not isinstance(refs, list):  # ObjectRefGenerator (streaming)
            return {"stream": self._register_stream(p, refs)}
        return {"refs": [self._track(p, r) for r in refs]}

    async def handle_ClientCancel(self, p: dict) -> dict:
        refs = self._client(p)
        ref = refs.get(p["ref"])
        if ref is None:
            return {"error": cloudpickle.dumps(
                RayTpuError(f"unknown client ref {p['ref']!r}"))}
        try:
            self._worker.cancel(ref, force=bool(p.get("force")))
        except Exception as e:
            return {"error": cloudpickle.dumps(e)}
        return {}

    async def handle_ClientKillActor(self, p: dict) -> dict:
        self._worker.kill_actor(bytes.fromhex(p["actor_id"]))
        return {}

    async def handle_ClientGetActorByName(self, p: dict) -> dict:
        found = self._worker.get_actor_by_name(p["name"])
        if found is None:
            return {"found": False}
        return {"found": True, "actor_id": found[0].hex()}

    async def handle_ClientGcsCall(self, p: dict) -> dict:
        # read-only control-plane passthrough (cluster_resources, nodes...)
        if p["method"] not in ("GetAllNodes", "Timeline"):
            return {"error": cloudpickle.dumps(
                RayTpuError(f"GCS method {p['method']!r} not allowed over ray://"))}
        return self._worker._gcs_call(p["method"], p.get("payload") or {})

    async def handle_ClientDisconnect(self, p: dict) -> dict:
        self._cleanup_session(p["client_id"], reason="disconnect")
        return {}


class ClientObjectRefGenerator:
    """Client-side view of a server-held ``ObjectRefGenerator``: iterating
    yields ObjectRefs (fetched one server round trip per item), matching
    the local streaming surface; ``close()`` cancels the producer."""

    def __init__(self, worker: "ClientWorker", stream_id: str):
        self._worker = worker
        self._stream_id = stream_id
        self._index = 0
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        return self._next_sync(timeout=None)

    def _next_sync(self, timeout: float | None):
        if self._closed:
            raise StopIteration
        reply = self._worker._call(
            "ClientStreamNext",
            {"stream": self._stream_id, "index": self._index, "timeout": timeout},
            timeout=None if timeout is None else timeout + 30.0)
        self._index += 1
        if reply.get("done"):
            self._closed = True
            raise StopIteration
        return self._worker._make_ref(reply["ref"])

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        _END = object()  # StopIteration cannot cross an asyncio Future

        def step():
            try:
                return self._next_sync(None)
            except StopIteration:
                return _END

        ref = await asyncio.get_running_loop().run_in_executor(None, step)
        if ref is _END:
            raise StopAsyncIteration
        return ref

    def completed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._worker._call("ClientStreamClose",
                               {"stream": self._stream_id}, timeout=10.0)
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ClientWorker:
    """Client-side stand-in for ``CoreWorker``: implements the method
    surface the public API uses, forwarding everything to the proxy."""

    def __init__(self, address: str):
        host_port = address[len(CLIENT_PREFIX):]
        self.client_id = uuid.uuid4().hex
        self.io = EventLoopThread("raytpu-client")
        self.rpc = RetryableRpcClient(host_port)
        self.node_id = "client"
        self.worker_id = f"client-{self.client_id[:12]}"
        self.job_id = JobID.from_int(0)
        self.actor_id = b""
        self.mode = "client"
        self._ref_lock = threading.Lock()
        self._local_refs: dict[bytes, str] = {}  # ObjectID binary -> server rid
        self._stop_ping = threading.Event()
        self._ping_thread: threading.Thread | None = None
        install_refcount_hooks(lambda r: None, lambda r: None)

    def _start_ping(self, interval: float) -> None:
        """Heartbeat so the proxy can tell a live-but-idle client from a
        crashed one (session reaping on the server side)."""
        def loop():
            while not self._stop_ping.wait(interval):
                try:
                    self._call("ClientPing", {}, timeout=15.0)
                except Exception:
                    pass  # transient; the retryable RPC client reconnects

        self._ping_thread = threading.Thread(
            target=loop, name="raytpu-client-ping", daemon=True)
        self._ping_thread.start()

    # ------------------------------------------------------------ plumbing
    def _call(self, method: str, payload: dict, timeout: float | None = 300.0) -> dict:
        from ..core.status import RayTaskError

        payload = {**payload, "client_id": self.client_id}
        reply = self.io.run_sync(self.rpc.call(method, payload, timeout))
        if reply.get("error"):
            err = cloudpickle.loads(reply["error"])
            if isinstance(err, RayTaskError):
                raise err.as_instanceof_cause()
            raise err
        return reply

    def _make_ref(self, rid: str) -> ObjectRef:
        # Client-side ObjectRefs carry a synthetic id; the server rid maps
        # back to the real ref.
        oid = ObjectID(bytes.fromhex(rid) + b"\x00" * (28 - len(rid) // 2))
        with self._ref_lock:
            self._local_refs[oid.binary()] = rid
        return ObjectRef(oid, owner_address="", _add_local_ref=False)

    def _rid(self, ref: ObjectRef) -> str:
        with self._ref_lock:
            rid = self._local_refs.get(ref.binary())
        if rid is None:
            raise RayTpuError("ObjectRef does not belong to this client session")
        return rid

    def _wire_args(self, args: tuple, kwargs: dict) -> list:
        out = []
        for kind, item in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(item, ObjectRef):
                blob = cloudpickle.dumps({"__client_ref__": True, "id": self._rid(item)})
            else:
                blob = cloudpickle.dumps(item)
            entry = {"blob": blob}
            if kind is not None:
                entry["key"] = kind
            out.append(entry)
        return out

    # ------------------------------------------------------------- surface
    def put(self, value: Any) -> ObjectRef:
        reply = self._call("ClientPut", {"blob": cloudpickle.dumps(value)})
        return self._make_ref(reply["ref"])

    def get(self, refs, timeout: float | None = None):
        reply = self._call("ClientGet", {
            "refs": [self._rid(r) for r in refs], "timeout": timeout,
        }, timeout=None if timeout is None else timeout + 30.0)
        return cloudpickle.loads(reply["blob"])

    def wait(self, refs, num_returns: int, timeout: float | None):
        rids = [self._rid(r) for r in refs]
        reply = self._call("ClientWait", {
            "refs": rids, "num_returns": num_returns, "timeout": timeout,
        }, timeout=None if timeout is None else timeout + 30.0)
        by_rid = dict(zip(rids, refs))
        return ([by_rid[r] for r in reply["ready"]],
                [by_rid[r] for r in reply["not_ready"]])

    def submit_task(self, fn, args, kwargs, **options):
        reply = self._call("ClientSubmitTask", {
            "fn": cloudpickle.dumps(fn),
            "args": self._wire_args(args, kwargs),
            "options": options,
        })
        if "stream" in reply:
            return ClientObjectRefGenerator(self, reply["stream"])
        return [self._make_ref(r) for r in reply["refs"]]

    def create_actor(self, cls, args, kwargs, **options) -> bytes:
        reply = self._call("ClientCreateActor", {
            "cls": cloudpickle.dumps(cls),
            "args": self._wire_args(args, kwargs),
            "options": options,
        })
        return bytes.fromhex(reply["actor_id"])

    def submit_actor_task(self, actor_id: bytes, method: str, args, kwargs,
                          *, num_returns=1, generator_backpressure: int = 0,
                          concurrency_group: str = ""):
        reply = self._call("ClientActorCall", {
            "actor_id": actor_id.hex(), "method": method,
            "args": self._wire_args(args, kwargs), "num_returns": num_returns,
            "generator_backpressure": generator_backpressure,
            "concurrency_group": concurrency_group,
        })
        if "stream" in reply:
            return ClientObjectRefGenerator(self, reply["stream"])
        return [self._make_ref(r) for r in reply["refs"]]

    def cancel(self, ref, *, force: bool = False) -> None:
        self._call("ClientCancel", {"ref": self._rid(ref), "force": force})

    def kill_actor(self, actor_id: bytes) -> None:
        self._call("ClientKillActor", {"actor_id": actor_id.hex()})

    def get_actor_by_name(self, name: str):
        reply = self._call("ClientGetActorByName", {"name": name})
        if not reply.get("found"):
            return None
        return bytes.fromhex(reply["actor_id"]), reply

    def register_actor_handle(self, actor_id: bytes, owned: bool) -> None:
        pass  # client handles never own cluster actors

    def deregister_actor_handle(self, actor_id: bytes) -> None:
        pass

    def _gcs_call(self, method: str, payload: dict, timeout: float | None = 30.0) -> dict:
        return self._call("ClientGcsCall", {"method": method, "payload": payload})

    def shutdown(self) -> None:
        self._stop_ping.set()
        if self._ping_thread is not None:
            self._ping_thread.join(timeout=2.0)
        try:
            self._call("ClientDisconnect", {}, timeout=5.0)
        except Exception:
            pass
        try:
            self.io.run_sync(self.rpc.close(), timeout=5)
        except Exception:
            pass
        self.io.stop()

    @property
    def current_task_id(self):
        return TaskID.nil()


def connect(address: str) -> ClientWorker:
    """``ray_tpu.init(address="ray://...")`` entry point."""
    worker = ClientWorker(address)
    # handshake: fails fast on a bad address, registers the per-client
    # job, and returns the heartbeat cadence
    reply = worker._call("ClientHello", {}, timeout=15.0)
    if reply.get("job_id") is not None:
        worker.job_id = JobID.from_int(reply["job_id"])
    worker._start_ping(float(reply.get("ping_interval_s") or 5.0))
    return worker
