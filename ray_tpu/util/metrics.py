"""Metrics: counters/gauges/histograms pushed to the GCS.

Equivalent of the reference's C++ stats layer (``src/ray/stats/metric.h:105``
Gauge/Count/Histogram on OpenCensus + per-node metrics agent): here every
process keeps a local registry and a flusher thread pushes snapshots to the
GCS (``ReportMetrics``), which aggregates per (name, tags) — queryable via
``get_metrics()`` / the CLI, exportable in Prometheus text format.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Sequence


# Default histogram boundaries, millisecond-scale: suitable for the
# latency/TTFT metrics this framework emits (serve_ttft_ms,
# serve_queue_wait_ms, ray_tpu_lease_stage_ms, ...).
LATENCY_MS_BOUNDARIES = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = (),
                 register: bool = True):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}
        # register=False keeps the metric out of the global registry (no
        # flusher push) — used by GCS-internal aggregations that are
        # merged into GetMetrics directly.
        if register:
            _registry_add(self)

    def _key(self, tags: dict | None) -> tuple:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {"name": self.name, "type": self.kind, "desc": self.description,
                 "tags": dict(zip(self.tag_keys, key)), "value": value}
                for key, value in self._values.items()
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        with self._lock:
            key = self._key(tags)
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(_Metric):
    """Fixed-boundary histogram; stores per-bucket counts + sum/count."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "", boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = (), register: bool = True):
        self.boundaries = tuple(boundaries) or LATENCY_MS_BOUNDARIES
        # set BEFORE super().__init__: registration makes this metric
        # visible to the flusher thread, which may snapshot immediately
        self._buckets: dict[tuple, list[int]] = {}
        self._counts: dict[tuple, int] = {}
        super().__init__(name, description, tag_keys, register)

    def observe(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            key = self._key(tags)
            buckets = self._buckets.setdefault(key, [0] * (len(self.boundaries) + 1))
            idx = sum(1 for b in self.boundaries if value > b)
            buckets[idx] += 1
            self._values[key] = self._values.get(key, 0.0) + value  # sum
            self._counts[key] = self._counts.get(key, 0) + 1

    def observe_many(self, values: Sequence[float],
                     tags: dict | None = None) -> None:
        """Bulk observe: one lock acquisition + tag-key resolution for a
        whole batch. The compiled-loop stall flush records ~192 samples
        per flush on a resident stage's tick path — per-sample observe()
        overhead there is recorder cost the ≤2% budget can't afford."""
        if not values:
            return
        boundaries = self.boundaries
        with self._lock:
            key = self._key(tags)
            buckets = self._buckets.setdefault(
                key, [0] * (len(boundaries) + 1))
            total = 0.0
            for v in values:
                # insertion point left of equals == |{b : b < v}|, the
                # same bucket observe()'s "v > b" scan picks
                buckets[bisect_left(boundaries, v)] += 1
                total += v
            self._values[key] = self._values.get(key, 0.0) + total
            self._counts[key] = self._counts.get(key, 0) + len(values)

    def snapshot(self) -> list[dict]:
        with self._lock:
            out = []
            for key, total in self._values.items():
                out.append({
                    "name": self.name, "type": "histogram",
                    "desc": self.description,
                    "tags": dict(zip(self.tag_keys, key)),
                    "value": total,
                    "count": self._counts.get(key, 0),
                    "buckets": list(self._buckets.get(key, [])),
                    "boundaries": list(self.boundaries),
                })
            return out


_registry_lock = threading.Lock()
_registry: list[_Metric] = []
_flusher: "_Flusher | None" = None


def _registry_add(metric: _Metric) -> None:
    with _registry_lock:
        _registry.append(metric)
    _ensure_flusher()


def snapshot_all() -> list[dict]:
    with _registry_lock:
        metrics = list(_registry)
    out: list[dict] = []
    for m in metrics:
        out.extend(m.snapshot())
    return out


class _Flusher:
    def __init__(self, interval_s: float = 5.0):
        self._interval = interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="raytpu-metrics")
        self._thread.start()

    def _loop(self) -> None:
        from ..core.worker import global_worker

        while True:
            time.sleep(self._interval)
            try:
                worker = global_worker()
                snap = snapshot_all()
                if not snap:
                    continue
                worker._gcs_call(
                    "ReportMetrics",
                    {"worker_id": worker.worker_id, "metrics": snap},
                    timeout=10.0,
                )
            except Exception:
                continue  # never let one bad cycle kill the flusher


def _ensure_flusher() -> None:
    global _flusher
    with _registry_lock:
        if _flusher is None:
            _flusher = _Flusher()


def get_metrics() -> list[dict]:
    """Cluster-wide aggregated metrics from the GCS."""
    from ..core.worker import global_worker

    return global_worker()._gcs_call("GetMetrics", {})["metrics"]


def prometheus_text(metrics: list[dict] | None = None) -> str:
    """Render metrics in the Prometheus exposition format: a ``# HELP`` /
    ``# TYPE`` header per metric family (Prometheus drops metadata — and
    Grafana shows no descriptions — without them), then the samples.
    Histograms emit the full ``_bucket``/``_sum``/``_count`` family
    (cumulative ``le`` buckets) so ``histogram_quantile`` works in
    Grafana."""
    def _esc(v) -> str:
        # Label-value escaping per the exposition format: one bad user tag
        # must not invalidate the whole scrape.
        return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    # Group rows by family so HELP/TYPE precede every sample of a name.
    families: dict[str, list[dict]] = {}
    for m in metrics if metrics is not None else get_metrics():
        families.setdefault(m["name"], []).append(m)

    lines = []
    for name, rows in families.items():
        kind = rows[0].get("type") or "gauge"
        kind = kind if kind in ("counter", "gauge", "histogram") else "untyped"
        desc = next((r.get("desc") for r in rows if r.get("desc")), "")
        if desc:
            lines.append(f"# HELP {name} {_esc(desc)}")
        lines.append(f"# TYPE {name} {kind}")
        for m in rows:
            tags = sorted((m.get("tags") or {}).items())
            base = ",".join(f'{k}="{_esc(v)}"' for k, v in tags)
            if m.get("type") == "histogram" and m.get("buckets"):
                cum = 0
                for bound, count in zip(
                        list(m.get("boundaries", [])) + ["+Inf"], m["buckets"]):
                    cum += count
                    le = f'le="{bound}"'
                    label = "{" + (base + "," if base else "") + le + "}"
                    lines.append(f"{name}_bucket{label} {cum}")
                label = f"{{{base}}}" if base else ""
                lines.append(f"{name}_sum{label} {m['value']}")
                lines.append(f"{name}_count{label} {m.get('count', cum)}")
                continue
            label = f"{{{base}}}" if base else ""
            lines.append(f"{name}{label} {m['value']}")
    return "\n".join(lines) + "\n"


def histogram_quantile(snapshot: dict, q: float) -> float | None:
    """Approximate quantile from one histogram snapshot row (linear
    interpolation within the bucket, the Prometheus convention). Returns
    None for an empty histogram."""
    buckets = snapshot.get("buckets") or []
    boundaries = list(snapshot.get("boundaries") or [])
    total = sum(buckets)
    if not total or not boundaries:
        return None
    target = q * total
    cum = 0
    lo = 0.0
    for i, count in enumerate(buckets):
        hi = boundaries[i] if i < len(boundaries) else boundaries[-1]
        if cum + count >= target and count > 0:
            frac = (target - cum) / count
            return lo + (hi - lo) * frac
        cum += count
        lo = hi
    return boundaries[-1]
