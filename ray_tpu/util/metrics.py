"""Metrics: counters/gauges/histograms pushed to the GCS.

Equivalent of the reference's C++ stats layer (``src/ray/stats/metric.h:105``
Gauge/Count/Histogram on OpenCensus + per-node metrics agent): here every
process keeps a local registry and a flusher thread pushes snapshots to the
GCS (``ReportMetrics``), which aggregates per (name, tags) — queryable via
``get_metrics()`` / the CLI, exportable in Prometheus text format.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}
        _registry_add(self)

    def _key(self, tags: dict | None) -> tuple:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {"name": self.name, "type": self.kind,
                 "tags": dict(zip(self.tag_keys, key)), "value": value}
                for key, value in self._values.items()
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        with self._lock:
            key = self._key(tags)
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(_Metric):
    """Fixed-boundary histogram; stores per-bucket counts + sum/count."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "", boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = ()):
        self.boundaries = tuple(boundaries) or (0.001, 0.01, 0.1, 1.0, 10.0, 100.0)
        # set BEFORE super().__init__: registration makes this metric
        # visible to the flusher thread, which may snapshot immediately
        self._buckets: dict[tuple, list[int]] = {}
        self._counts: dict[tuple, int] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            key = self._key(tags)
            buckets = self._buckets.setdefault(key, [0] * (len(self.boundaries) + 1))
            idx = sum(1 for b in self.boundaries if value > b)
            buckets[idx] += 1
            self._values[key] = self._values.get(key, 0.0) + value  # sum
            self._counts[key] = self._counts.get(key, 0) + 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            out = []
            for key, total in self._values.items():
                out.append({
                    "name": self.name, "type": "histogram",
                    "tags": dict(zip(self.tag_keys, key)),
                    "value": total,
                    "count": self._counts.get(key, 0),
                    "buckets": list(self._buckets.get(key, [])),
                    "boundaries": list(self.boundaries),
                })
            return out


_registry_lock = threading.Lock()
_registry: list[_Metric] = []
_flusher: "_Flusher | None" = None


def _registry_add(metric: _Metric) -> None:
    with _registry_lock:
        _registry.append(metric)
    _ensure_flusher()


def snapshot_all() -> list[dict]:
    with _registry_lock:
        metrics = list(_registry)
    out: list[dict] = []
    for m in metrics:
        out.extend(m.snapshot())
    return out


class _Flusher:
    def __init__(self, interval_s: float = 5.0):
        self._interval = interval_s
        self._thread = threading.Thread(target=self._loop, daemon=True, name="raytpu-metrics")
        self._thread.start()

    def _loop(self) -> None:
        from ..core.worker import global_worker

        while True:
            time.sleep(self._interval)
            try:
                worker = global_worker()
                snap = snapshot_all()
                if not snap:
                    continue
                worker._gcs_call(
                    "ReportMetrics",
                    {"worker_id": worker.worker_id, "metrics": snap},
                    timeout=10.0,
                )
            except Exception:
                continue  # never let one bad cycle kill the flusher


def _ensure_flusher() -> None:
    global _flusher
    with _registry_lock:
        if _flusher is None:
            _flusher = _Flusher()


def get_metrics() -> list[dict]:
    """Cluster-wide aggregated metrics from the GCS."""
    from ..core.worker import global_worker

    return global_worker()._gcs_call("GetMetrics", {})["metrics"]


def prometheus_text(metrics: list[dict] | None = None) -> str:
    """Render metrics in the Prometheus exposition format. Histograms emit
    the full ``_bucket``/``_sum``/``_count`` family (cumulative ``le``
    buckets) so ``histogram_quantile`` works in Grafana."""
    def _esc(v) -> str:
        # Label-value escaping per the exposition format: one bad user tag
        # must not invalidate the whole scrape.
        return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    lines = []
    for m in metrics if metrics is not None else get_metrics():
        tags = sorted((m.get("tags") or {}).items())
        base = ",".join(f'{k}="{_esc(v)}"' for k, v in tags)
        if m.get("type") == "histogram" and m.get("buckets"):
            cum = 0
            for bound, count in zip(
                    list(m.get("boundaries", [])) + ["+Inf"], m["buckets"]):
                cum += count
                le = f'le="{bound}"'
                label = "{" + (base + "," if base else "") + le + "}"
                lines.append(f"{m['name']}_bucket{label} {cum}")
            label = f"{{{base}}}" if base else ""
            lines.append(f"{m['name']}_sum{label} {m['value']}")
            lines.append(f"{m['name']}_count{label} {m.get('count', cum)}")
            continue
        label = f"{{{base}}}" if base else ""
        lines.append(f"{m['name']}{label} {m['value']}")
    return "\n".join(lines) + "\n"
