"""User-facing utilities: placement groups, scheduling strategies,
actor pools, distributed queues, multiprocessing.Pool compatibility.

Reference: ``python/ray/util/placement_group.py``,
``python/ray/util/scheduling_strategies.py``, ``util/actor_pool.py``,
``util/queue.py``, ``util/multiprocessing/pool.py``.
"""

from .actor_pool import ActorPool
from .placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "SpreadSchedulingStrategy",
]
