"""User-facing utilities: placement groups, scheduling strategies.

Reference: ``python/ray/util/placement_group.py``,
``python/ray/util/scheduling_strategies.py``.
"""

from .placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "SpreadSchedulingStrategy",
]
