"""``multiprocessing.Pool``-compatible API over cluster actors.

Equivalent of the reference's ``python/ray/util/multiprocessing/pool.py``:
drop-in ``Pool`` with ``map``/``imap``/``imap_unordered``/``apply`` /
``apply_async`` + ``AsyncResult``, so stdlib-Pool code scales past one
machine without rewriting. Each pool worker is an actor executing
pickled callables.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable

from ..core import api as ray
from .actor_pool import ActorPool


class _PoolWorker:
    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(item) for item in chunk]


class AsyncResult:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: float | None = None):
        return ray.get(self._ref, timeout=timeout)

    def wait(self, timeout: float | None = None) -> None:
        ray.wait([self._ref], num_returns=1, timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray.wait([self._ref], num_returns=1, timeout=0)
        return bool(ready)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")  # stdlib Pool semantics
        try:
            self.get(timeout=60)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: int | None = None, *, actor_options: dict | None = None):
        if processes is None:
            total = ray.cluster_resources().get("CPU", 1)
            processes = max(1, int(total))
        opts = {"num_cpus": 1, **(actor_options or {})}
        cls = ray.remote(_PoolWorker)
        self._actors = [cls.options(**opts).remote() for _ in range(processes)]
        self._pool = ActorPool(self._actors)
        self._closed = False
        self._rr = itertools.count()

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()

    def _check(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    # ------------------------------------------------------------------ apply
    def apply(self, fn: Callable, args: tuple = (), kwargs: dict | None = None):
        return self.apply_async(fn, args, kwargs).get()

    def apply_async(self, fn: Callable, args: tuple = (), kwargs: dict | None = None) -> AsyncResult:
        self._check()
        # Round-robin over actors (no result ordering needed for applies).
        actor = self._actors[next(self._rr) % len(self._actors)]
        return AsyncResult(actor.run.remote(fn, args, kwargs))

    # -------------------------------------------------------------------- map
    def map(self, fn: Callable, iterable: Iterable, chunksize: int | None = None) -> list:
        return list(self.imap(fn, iterable, chunksize))

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int | None = None):
        self._check()
        for chunk_result in self._pool.map(
            lambda actor, chunk: actor.run_batch.remote(fn, chunk),
            _chunks(iterable, chunksize or 1),
        ):
            yield from chunk_result

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int | None = None):
        self._check()
        for chunk_result in self._pool.map_unordered(
            lambda actor, chunk: actor.run_batch.remote(fn, chunk),
            _chunks(iterable, chunksize or 1),
        ):
            yield from chunk_result

    def starmap(self, fn: Callable, iterable: Iterable) -> list:
        return self.map(lambda args: fn(*args), iterable)


def _chunks(iterable: Iterable, size: int) -> Iterable[list]:
    it = iter(iterable)
    while True:
        chunk = list(itertools.islice(it, size))
        if not chunk:
            return
        yield chunk
