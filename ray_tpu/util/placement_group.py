"""Placement groups: atomic reservation of resource bundles across nodes.

Reference: ``python/ray/util/placement_group.py`` (placement_group:147,
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD:16-19). The GCS does the 2PC bundle
reservation (``ray_tpu/core/gcs.py handle_CreatePlacementGroup``,
mirroring ``gcs_placement_group_scheduler.h:117-119``).

TPU idiom: a ``STRICT_PACK`` group over per-host ``{"TPU": n}`` bundles
plus one ``TPU-{type}-head`` bundle is how a whole slice is claimed as an
atomic unit (reference scheme: ``_private/accelerators/tpu.py:70-192``).
"""

from __future__ import annotations

import time

from ..core.ids import PlacementGroupID
from ..core.status import PlacementGroupUnschedulableError, RayTpuError
from ..core.worker import global_worker


class PlacementGroup:
    """Handle to a created placement group."""

    def __init__(self, pg_id: bytes, bundles: list[dict], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def _state(self) -> dict:
        reply = global_worker()._gcs_call(
            "GetPlacementGroup", {"pg_id": self.id.hex()}
        )
        return reply.get("pg") or {}

    def ready(self) -> bool:
        return self._state().get("state") == "CREATED"

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            state = self._state().get("state")
            if state == "CREATED":
                return True
            if state == "INFEASIBLE":
                raise PlacementGroupUnschedulableError(
                    f"placement group {self.id.hex()} is infeasible: "
                    f"bundles {self.bundles} exceed any node's total resources"
                )
            time.sleep(0.05)
        return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    *,
    name: str = "",
) -> PlacementGroup:
    """Create a placement group. Reference: placement_group.py:147."""
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"unknown placement strategy {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    worker = global_worker()
    pg_id = PlacementGroupID.of(worker.job_id if hasattr(worker, "job_id") else None)
    worker._gcs_call(
        "CreatePlacementGroup",
        {
            "pg_id": pg_id.binary().hex(),
            "bundles": bundles,
            "strategy": strategy,
            "name": name,
        },
    )
    return PlacementGroup(pg_id.binary(), bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker()._gcs_call("RemovePlacementGroup", {"pg_id": pg.id.hex()})
