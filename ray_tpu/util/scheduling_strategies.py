"""Scheduling strategy objects.

Reference: ``python/ray/util/scheduling_strategies.py`` (
PlacementGroupSchedulingStrategy / NodeAffinitySchedulingStrategy /
NodeLabelSchedulingStrategy). ``to_wire()`` produces the dict consumed by
the raylet scheduler policies (``ray_tpu/core/scheduling.py``).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object  # PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    @property
    def placement_group_id(self) -> bytes:
        return self.placement_group.id

    def to_wire(self) -> dict:
        return {
            "type": "placement_group",
            "pg_id": self.placement_group.id.hex()
            if isinstance(self.placement_group.id, bytes)
            else self.placement_group.id,
            "bundle_index": self.placement_group_bundle_index,
        }


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def to_wire(self) -> dict:
        return {"type": "node_affinity", "node_id": self.node_id, "soft": self.soft}


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    hard: dict | None = None
    soft: dict | None = None

    def to_wire(self) -> dict:
        return {"type": "node_label", "hard": self.hard or {}, "soft": self.soft or {}}


@dataclasses.dataclass
class SpreadSchedulingStrategy:
    def to_wire(self) -> dict:
        return {"type": "spread"}
