"""ActorPool: distribute a stream of tasks over a fixed set of actors.

Equivalent of the reference's ``python/ray/util/actor_pool.py``: submit
``fn(actor, value)`` calls to whichever actor is free, fetch results in
submission order (``get_next``) or completion order
(``get_next_unordered``), and ``map``/``map_unordered`` over iterables.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from ..core import api as ray


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value: Any) -> None:
        """``fn(actor, value) -> ObjectRef``; queued if all actors busy."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def _return_actor(self, future) -> None:
        actor = self._future_to_actor.pop(future, None)
        if actor is not None:
            self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float | None = None):
        """Next result in SUBMISSION order. On timeout the task stays
        pending (retryable); on task error the actor still returns to the
        pool before the exception propagates."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        idx = self._next_return_index
        future = self._index_to_future[idx]
        ready, _ = ray.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        del self._index_to_future[idx]
        self._next_return_index += 1
        self._return_actor(future)
        return ray.get(future)

    def get_next_unordered(self, timeout: float | None = None):
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = ray.wait(list(self._index_to_future.values()), num_returns=1,
                            timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        future = ready[0]
        for idx, fut in list(self._index_to_future.items()):
            if fut is future or fut == future:
                del self._index_to_future[idx]
                break
        self._return_actor(future)
        return ray.get(future)

    def map(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
