"""Core scalability microbenchmarks.

Equivalent of the reference's ``python/ray/_private/ray_perf.py:93``: a
fixed suite of control-plane microbenchmarks (task submission, actor
calls, put/get by size, many-task / many-actor / many-PG stress) whose
numbers are tracked in ``PERF.md`` against the reference's published
envelope (BASELINE.md). Run: ``python -m ray_tpu._perf [--quick]``.
"""

from __future__ import annotations

import json
import sys
import time


def timeit(name: str, fn, n: int, results: list, *, unit: str = "ops/s") -> float:
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    rate = n / dt
    results.append({"name": name, "rate": round(rate, 1), "n": n,
                    "seconds": round(dt, 3), "unit": unit})
    print(f"{name:<44} {rate:>12,.1f} {unit}  ({n} in {dt:.2f}s)", flush=True)
    return rate


def main(quick: bool = False, stress: bool = False) -> list[dict]:
    import ray_tpu

    scale = 0.2 if quick else 1.0
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    results: list[dict] = []

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    def noop_arg(x):
        return x

    # Warmup: start workers, prime lease pipelines.
    ray_tpu.get([noop.remote() for _ in range(20)], timeout=120)

    n = int(500 * scale)
    timeit("tasks: submit+get sync (1 client)",
           lambda: [ray_tpu.get(noop.remote(), timeout=60) for _ in range(n)],
           n, results)

    n = int(2000 * scale)
    timeit("tasks: batch submit then get",
           lambda: ray_tpu.get([noop.remote() for _ in range(n)], timeout=300),
           n, results)

    n = int(1000 * scale)
    timeit("tasks: 1KB arg roundtrip",
           lambda: ray_tpu.get([noop_arg.remote(b"x" * 1024) for _ in range(n)],
                               timeout=300),
           n, results)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        async def ainc(self):
            self.n += 1
            return self.n

    actor = Counter.remote()
    ray_tpu.get(actor.inc.remote(), timeout=60)

    n = int(500 * scale)
    timeit("actor: calls sync (1 actor, 1 client)",
           lambda: [ray_tpu.get(actor.inc.remote(), timeout=60) for _ in range(n)],
           n, results)

    n = int(2000 * scale)
    timeit("actor: batch calls then get",
           lambda: ray_tpu.get([actor.inc.remote() for _ in range(n)], timeout=300),
           n, results)

    async_actor = Counter.options(max_concurrency=16).remote()
    ray_tpu.get(async_actor.ainc.remote(), timeout=60)
    n = int(2000 * scale)
    timeit("actor: async-method batch calls (conc=16)",
           lambda: ray_tpu.get([async_actor.ainc.remote() for _ in range(n)],
                               timeout=300),
           n, results)

    # put/get by size
    for size, label, count in [(1024, "1KB", 1000), (1 << 20, "1MB", 200),
                               (10 << 20, "10MB", 40)]:
        count = max(5, int(count * scale))
        payload = b"x" * size
        refs: list = []

        def do_puts():
            refs.extend(ray_tpu.put(payload) for _ in range(count))

        timeit(f"object: put {label}", do_puts, count, results)
        timeit(f"object: get {label}",
               lambda: [ray_tpu.get(r, timeout=60) for r in refs], count, results)
        del refs

    # many-task stress: wide fan-out through the scheduler
    n = int(5000 * scale)
    timeit(f"stress: {n} tiny tasks end-to-end",
           lambda: ray_tpu.get([noop.remote() for _ in range(n)], timeout=600),
           n, results)

    # many-actor stress: creation + one call each
    n = int(40 * scale) or 8

    def many_actors():
        # fractional CPUs: this measures the scheduler, not core count
        actors = [Counter.options(num_cpus=0.05).remote() for _ in range(n)]
        ray_tpu.get([a.inc.remote() for a in actors], timeout=300)
        for a in actors:
            ray_tpu.kill(a)

    timeit(f"stress: create+call+kill {n} actors", many_actors, n, results,
           unit="actors/s")

    # placement-group churn
    from ray_tpu.util import placement_group, remove_placement_group

    n = max(3, int(20 * scale))

    def pg_churn():
        for _ in range(n):
            pg = placement_group([{"CPU": 1}], strategy="PACK")
            assert pg.wait(timeout_seconds=30)
            remove_placement_group(pg)

    timeit(f"stress: {n} PG create/ready/remove cycles", pg_churn, n, results,
           unit="pgs/s")

    if stress:
        # The release-envelope shapes (BASELINE.md rows: 1M queued tasks,
        # 40k actors) scaled to one host: a deep queued-task drain and a
        # wide actor wave.
        n = 100_000
        timeit(f"stress: {n} queued tasks drain",
               lambda: ray_tpu.get([noop.remote() for _ in range(n)],
                                   timeout=1800),
               n, results)

        n = 500

        def actor_wave():
            actors = [Counter.options(num_cpus=0.001).remote() for _ in range(n)]
            ray_tpu.get([a.inc.remote() for a in actors], timeout=1200)
            for a in actors:
                ray_tpu.kill(a)

        timeit(f"stress: create+call+kill {n} actors", actor_wave, n, results,
               unit="actors/s")

        # single-node envelope rows (BASELINE.md: object args to one task,
        # returns from one task, plasma objects in one ray.get —
        # reference release/benchmarks/single_node/test_single_node.py)
        n_args = 2000

        @ray_tpu.remote
        def count_args(*args):
            return len(args)

        arg_refs = [ray_tpu.put(i) for i in range(n_args)]

        def many_args():
            assert ray_tpu.get(count_args.remote(*arg_refs), timeout=600) == n_args

        timeit(f"stress: {n_args} object args to one task", many_args, n_args,
               results, unit="args/s")
        del arg_refs

        n_rets = 1000

        @ray_tpu.remote(num_returns=n_rets)
        def many_returns():
            return list(range(n_rets))

        def returns_wave():
            refs = many_returns.remote()
            assert ray_tpu.get(refs[-1], timeout=600) == n_rets - 1

        timeit(f"stress: {n_rets} returns from one task", returns_wave, n_rets,
               results, unit="returns/s")

        n_get = 5000
        put_refs = [ray_tpu.put(i) for i in range(n_get)]

        def bulk_get():
            vals = ray_tpu.get(put_refs, timeout=600)
            assert vals[-1] == n_get - 1

        timeit(f"stress: one ray.get of {n_get} objects", bulk_get, n_get,
               results, unit="objects/s")

    return results


if __name__ == "__main__":
    quick = "--quick" in sys.argv
    out = main(quick=quick, stress="--stress" in sys.argv)
    print(json.dumps({"perf": out}))
