"""Compiled graphs: pre-wired actor pipelines over mutable shm channels.

Equivalent of the reference's accelerated DAGs
(``python/ray/dag/compiled_dag_node.py:795`` + experimental mutable-
object channels): build a DAG with ``actor.method.bind(...)``, compile
it once, then ``execute()`` repeatedly with NO per-call task submission
— each actor runs a resident executor loop that spins on its input
channels, so steady-state latency is channel write + compute + channel
read. The channel is a seqlock'd mmap in /dev/shm (``channel.py``)
standing in for the reference's versioned mutable plasma objects.
"""

from .channel import Channel, RingChannel
from .compiled import CompiledDAG
from .loop import CompiledLoop, compile_loop
from .nodes import AllReduceNode, ClassMethodNode, InputNode, MultiOutputNode, collective

__all__ = ["AllReduceNode", "Channel", "CompiledDAG", "CompiledLoop",
           "ClassMethodNode", "InputNode", "MultiOutputNode", "RingChannel",
           "collective", "compile_loop"]
