"""DAG compilation + the resident per-actor executor loop.

Reference ``python/ray/dag/compiled_dag_node.py:795`` (CompiledDAG):
compile() walks the graph, allocates one channel per producing node,
and installs a loop on every participating actor via ``__ray_call__``.
``execute()`` is then a channel write + channel read — zero task
submissions at steady state. Errors serialize through the channels and
re-raise at the driver; ``teardown()`` closes the input channels, which
cascades ChannelClosed through every loop.
"""

from __future__ import annotations

import os
import struct
import tempfile
import uuid

from ..core import serialization
from ..core.status import RayTaskError
from .channel import Channel, ChannelClosed, TcpChannelReader, TcpChannelServer
from .nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode


def _open_reader(desc, capacity: int):
    """Open the reader end of a channel descriptor: ("shm", path) or
    ("tcp", address)."""
    if desc[0] == "tcp":
        return TcpChannelReader(desc[1])
    return Channel(desc[1], capacity)

# Channel payload = [u32 meta_len][meta][blob] using the core serializer,
# so DAG values get the same encoding (and error framing) as every other
# object in the system — one format, not two.
_LEN = struct.Struct("<I")


def _pack(value) -> bytes:
    meta, blob, _ = serialization.serialize(value)
    return _LEN.pack(len(meta)) + meta + bytes(blob)


def _pack_error(error: BaseException) -> bytes:
    meta, blob, _ = serialization.serialize_error(error)
    return _LEN.pack(len(meta)) + meta + bytes(blob)


def _unpack(payload: bytes):
    n = _LEN.unpack_from(payload)[0]
    meta = bytes(payload[_LEN.size : _LEN.size + n])
    value = serialization.deserialize(meta, payload[_LEN.size + n :])
    return value, meta == serialization.META_ERROR


def _probe_node(instance) -> str:
    """Phase-0 placement probe (runs on the actor)."""
    from ..core.worker import global_worker

    return global_worker().node_id


def _routable_host() -> str:
    """This process's routable host, derived from the worker address (which
    tracks the raylet's registered interface, not loopback)."""
    from ..core.worker import global_worker

    return global_worker().address.rpartition(":")[0] or "127.0.0.1"


def _create_out_server(instance) -> str:
    """Phase-1 for a cross-node producer: create the TCP channel server in
    the actor process (stashed on the instance for the phase-2 loop) and
    return its address."""
    from .channel import TcpChannelServer

    server = TcpChannelServer(advertise=_routable_host())
    instance.__dict__["_dag_out_server"] = server
    return server.address


def _actor_loop(instance, method_name: str, in_specs: list, out_desc,
                capacity: int) -> str:
    """Runs ON the actor (shipped via __ray_call__): spin on input
    channels, apply the bound method, write the result. ``in_specs`` is a
    list of ("chan", desc) / ("const", value) in positional order, where
    desc is ("shm", path) or ("tcp", address)."""
    channels = {
        desc: _open_reader(desc, capacity) for kind, desc in in_specs if kind == "chan"
    }
    if out_desc[0] == "tcp":
        out = instance.__dict__.pop("_dag_out_server")
    else:
        # Readiness marker: compile() blocks until every loop has one, so
        # execute() timeouts never race actor-creation latency.
        with open(out_desc[1] + ".ready", "w") as f:
            f.write("1")
        out = Channel(out_desc[1], capacity)
    cursors = {desc: 0 for desc in channels}
    method = getattr(instance, method_name)
    try:
        while True:
            args, upstream_error = [], None
            for kind, item in in_specs:
                if kind == "const":
                    args.append(item)
                    continue
                payload, seq = channels[item].read(cursors[item])
                cursors[item] = seq
                value, is_error = _unpack(payload)
                if is_error and upstream_error is None:
                    upstream_error = value
                args.append(value)
            if upstream_error is not None:
                out.write(_pack_error(upstream_error))
                continue
            try:
                result = method(*args)
                payload = _pack(result)  # inside try: unpicklable results
                if len(payload) > capacity:
                    raise ValueError(
                        f"{method_name} result of {len(payload)} bytes exceeds "
                        f"channel capacity {capacity}; raise max_buffer_size"
                    )
            except Exception as e:  # serialize through the pipe, keep looping
                import traceback

                payload = _pack_error(RayTaskError(method_name, traceback.format_exc(), e))
            out.write(payload)
    except ChannelClosed:
        out.close_writer()  # cascade teardown downstream
        return "closed"
    finally:
        for ch in channels.values():
            ch.close()
        out.close()


class CompiledDAG:
    def __init__(self, output_node: DAGNode, max_buffer_size: int | None = None):
        from ..core.config import get_config

        self.capacity = max_buffer_size or get_config().dag_channel_capacity
        self._dir: str | None = None
        self._input_node: InputNode | None = None
        self._outputs: list[ClassMethodNode] = []
        self._loop_refs = []
        self._channels: dict[int, str] = {}  # id(node) -> channel path
        self._torn_down = False

        if isinstance(output_node, MultiOutputNode):
            self._outputs = list(output_node.outputs)
        else:
            self._outputs = [output_node]
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise TypeError("DAG outputs must be actor method nodes")

        # Validate the whole graph BEFORE allocating anything in /dev/shm —
        # a rejected compile must not leak RAM-backed files.
        order = self._toposort()
        if self._input_node is None:
            raise ValueError("compiled DAG needs an InputNode")
        # One node per actor: each node parks a never-returning executor
        # task on its actor, so a second node on the same actor could never
        # start (max_concurrency=1 sequencing) — reject early instead of
        # hanging compile.
        # Collective nodes materialize their hidden reducer actors now
        # (they must exist before placement probing / loop install).
        self._owned_actors = []
        for node in order:
            if hasattr(node, "materialize_actor"):
                node.materialize_actor()
                if getattr(node, "_owned_actor", False):
                    self._owned_actors.append(node.actor)
        seen_actors: dict[bytes, str] = {}
        for node in order:
            if not isinstance(node, ClassMethodNode):
                continue
            actor_id = node.actor._actor_id
            if actor_id in seen_actors:
                raise ValueError(
                    f"actor used by both '{seen_actors[actor_id]}' and "
                    f"'{node.method_name}' — a compiled DAG supports one node "
                    "per actor (create a separate actor per stage)"
                )
            seen_actors[actor_id] = node.method_name

        # Placement: each producer's channel is shm when every endpoint
        # shares its node, TCP otherwise (reference: shared_memory_channel
        # falls back to its cross-node transport per edge).
        from ..core import api as ray

        driver_node = ray.get_runtime_context().node_id
        node_of: dict[int, str] = {id(self._input_node): driver_node}
        for node in order:
            if isinstance(node, ClassMethodNode):
                node_of[id(node)] = ray.get(
                    node.actor.__ray_call__.remote(_probe_node), timeout=60)
        consumers: dict[int, list[str]] = {id(n): [] for n in order}
        for node in order:
            if isinstance(node, ClassMethodNode):
                for up in node.upstream():
                    consumers[id(up)].append(node_of[id(node)])
        for out in self._outputs:
            consumers[id(out)].append(driver_node)  # driver reads outputs

        self._dir = tempfile.mkdtemp(prefix="raytpu_dag_", dir="/dev/shm")
        self._cross_node: set[int] = set()
        # One channel per producer (InputNode + every method node). The
        # descriptor is ("shm", path) or ("tcp", address).
        for node in order:
            local = all(c == node_of[id(node)] for c in consumers[id(node)])
            if local:
                path = os.path.join(self._dir, f"ch_{uuid.uuid4().hex[:10]}")
                Channel(path, self.capacity, create=True).close()
                self._channels[id(node)] = ("shm", path)
                continue
            self._cross_node.add(id(node))
            if node is self._input_node:
                # Advertise the driver's routable node host so consumer
                # actors on other hosts connect back to the driver rather
                # than their own loopback.
                self._input_server = TcpChannelServer(advertise=_routable_host())
                self._channels[id(node)] = ("tcp", self._input_server.address)
            else:
                # Phase 1: the producing actor creates its server NOW so
                # consumers know the address before their loops install.
                addr = ray.get(
                    node.actor.__ray_call__.remote(_create_out_server), timeout=60)
                self._channels[id(node)] = ("tcp", addr)

        in_desc = self._channels[id(self._input_node)]
        self._input = (self._input_server if in_desc[0] == "tcp"
                       else Channel(in_desc[1], self.capacity))
        self._out_channels = [
            _open_reader(self._channels[id(node)], self.capacity)
            for node in self._outputs
        ]
        self._out_cursors = [0] * len(self._outputs)

        # Phase 2: install executor loops (upstream-last so consumers are
        # listening before producers can emit).
        for node in order:
            if not isinstance(node, ClassMethodNode):
                continue
            in_specs = []
            for arg in node.args:
                if isinstance(arg, DAGNode):
                    in_specs.append(("chan", self._channels[id(arg)]))
                else:
                    in_specs.append(("const", arg))
            ref = node.actor.__ray_call__.remote(
                _actor_loop, node.method_name, in_specs,
                self._channels[id(node)], self.capacity,
            )
            self._loop_refs.append(ref)
        from ..core.config import get_config
        self._wait_ready(timeout=get_config().dag_ready_timeout_s)

    def _wait_ready(self, timeout: float) -> None:
        """Block until every executor loop has opened its channels, so
        execute() timeouts are about execution and loop-install failures
        (e.g. actor died) surface as real errors. Actor creation cannot be
        starved by task load anymore — the raylet admits actor-creation
        leases ahead of task leases (raylet._acquire_resources_queued) —
        so a miss here indicates a real failure, not scheduler unfairness."""
        import time

        from ..core import api as ray

        # Cross-node producers have no driver-visible marker file; their
        # phase-1 server creation already proved the actor alive, and the
        # loop-ref liveness check below covers install crashes.
        markers = [
            self._channels[id(node)][1] + ".ready"
            for node in self._channels_nodes()
            if self._channels[id(node)][0] == "shm"
        ]
        deadline = time.monotonic() + timeout
        while True:
            if all(os.path.exists(m) for m in markers):
                return
            # A loop ref completing at this stage means its install DIED.
            done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
            if done:
                ray.get(done[0])  # raises the real cause
                raise RuntimeError("DAG executor loop exited during compile")
            if time.monotonic() > deadline:
                missing = [m for m in markers if not os.path.exists(m)]
                raise TimeoutError(
                    f"{len(missing)} DAG executor loop(s) not ready after "
                    f"{timeout}s: {missing[:3]}"
                )
            time.sleep(0.01)

    def _channels_nodes(self) -> list[ClassMethodNode]:
        return [n for n in self._iter_nodes() if isinstance(n, ClassMethodNode)]

    def _iter_nodes(self):
        seen: set[int] = set()

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            yield node
            if isinstance(node, ClassMethodNode):
                for up in node.upstream():
                    yield from visit(up)

        for out in self._outputs:
            yield from visit(out)

    def _toposort(self) -> list[DAGNode]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(node: DAGNode) -> None:
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, InputNode):
                if self._input_node is not None and self._input_node is not node:
                    raise ValueError("a compiled DAG supports one InputNode")
                self._input_node = node
                order.append(node)
                return
            if isinstance(node, ClassMethodNode):
                if not node.upstream():
                    raise ValueError(
                        f"{node.method_name}.bind(...) has no upstream node — "
                        "a compiled node needs at least one DAG input or it "
                        "would loop forever"
                    )
                for up in node.upstream():
                    visit(up)
                order.append(node)
                return
            raise TypeError(f"unsupported DAG node {type(node).__name__}")

        for out in self._outputs:
            visit(out)
        return order

    # ---------------------------------------------------------------- execute
    def execute(self, value, timeout: float = 60.0):
        """Push one input through the graph; returns the output (or tuple
        of outputs for MultiOutputNode). Synchronous: one round at a time.

        ``timeout`` is ONE deadline for the whole round (not per output
        channel). A timed-out round poisons the pipeline — the parked
        executors may still be mid-compute, and their late results would
        desync every later round's cursors — so the DAG tears itself
        down: this call raises TimeoutError, and every subsequent
        ``execute`` raises ChannelClosed (never hangs, never returns a
        stale round)."""
        if self._torn_down:
            raise ChannelClosed("DAG has been torn down")
        import time as _time

        deadline = _time.monotonic() + timeout
        self._input.write(_pack(value))
        # Drain EVERY output before raising: skipping channels on error
        # would leave their cursors one round behind and desync all later
        # executes (they would read this round's stale payloads).
        results, first_error = [], None
        for i, ch in enumerate(self._out_channels):
            try:
                payload, seq = ch.read(
                    self._out_cursors[i],
                    timeout=max(0.0, deadline - _time.monotonic()))
            except TimeoutError:
                # Surface a dead loop's real error instead of the timeout.
                from ..core import api as ray

                done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
                try:
                    if done:
                        ray.get(done[0])
                    raise
                finally:
                    # Tear down rather than leave a desynced pipeline: the
                    # executor blocked on this round would complete it
                    # AFTER our cursors moved on.
                    self.teardown(timeout=1.0)
            self._out_cursors[i] = seq
            result, is_error = _unpack(payload)
            if is_error and first_error is None:
                first_error = result
            results.append(result)
        if first_error is not None:
            raise (first_error.as_instanceof_cause()
                   if isinstance(first_error, RayTaskError) else first_error)
        return results[0] if len(results) == 1 else tuple(results)

    # --------------------------------------------------------------- teardown
    def teardown(self, timeout: float = 30.0) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        # Defensive getattr: __del__ may run on a DAG whose __init__ raised
        # partway (validation errors) — clean what exists.
        input_ch = getattr(self, "_input", None)
        if input_ch is not None:
            input_ch.close_writer()  # ChannelClosed cascades through loops
            from ..core import api as ray

            try:
                ray.get(self._loop_refs, timeout=timeout)
            except Exception:
                pass
            input_ch.close()
        for ch in getattr(self, "_out_channels", []):
            ch.close()
        for actor in getattr(self, "_owned_actors", []):
            try:
                from ..core import api as ray

                ray.kill(actor)
            except Exception:
                pass
        if self._dir is not None:
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)

    def __del__(self):
        try:
            self.teardown(timeout=1.0)
        except Exception:
            pass
