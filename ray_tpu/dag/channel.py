"""Mutable single-writer channel over an mmap'd /dev/shm file.

Equivalent of the reference's mutable-object channels
(``src/ray/core_worker/experimental_mutable_object_manager.h``): a
fixed-capacity buffer a writer overwrites in place, readers follow a
sequence counter. Layout:

    [u64 seq][u64 len][payload ... capacity]

``seq`` is odd WHILE a write is in progress (seqlock): readers that see
an odd seq, or whose second seq read differs from the first, retry — so
a torn read is impossible without any cross-process lock. A ``len`` of
``STOP`` tears the channel down (executor loops exit).
"""

from __future__ import annotations

import mmap
import os
import struct
import time

_HEADER = struct.Struct("<QQ")
STOP = 0xFFFFFFFFFFFFFFFF


class ChannelClosed(Exception):
    pass


class Channel:
    def __init__(self, path: str, capacity: int, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HEADER.size + capacity
        if create:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            os.ftruncate(fd, total)
        else:
            fd = os.open(path, os.O_RDWR)
        self._fd = fd
        self._mm = mmap.mmap(fd, total)
        self._view = memoryview(self._mm)

    # ------------------------------------------------------------------ write
    def write(self, payload: bytes) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity} (raise max_buffer_size at compile time)"
            )
        seq, _ = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, len(payload))  # odd: in progress
        self._view[_HEADER.size : _HEADER.size + len(payload)] = payload
        _HEADER.pack_into(self._view, 0, seq + 2, len(payload))  # even: committed

    def close_writer(self) -> None:
        # Two-phase, but the STOP length lands while seq is still ODD and
        # the commit touches ONLY the seq word: a torn header can therefore
        # never pair the new even seq with the stale length (which would
        # re-consume the final payload and skip the STOP forever). write()
        # is safe with its wider commit because its odd phase pre-writes
        # the same length the commit re-writes.
        seq, _length = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, STOP)  # odd: STOP staged
        struct.pack_into("<Q", self._view, 0, seq + 2)   # commit seq alone

    # ------------------------------------------------------------------- read
    def read(self, last_seq: int, timeout: float | None = None) -> tuple[bytes, int]:
        """Block (spin) until a version newer than ``last_seq`` commits;
        returns (payload, seq). Raises ChannelClosed on teardown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 1e-5
        while True:
            seq, length = _HEADER.unpack_from(self._view, 0)
            if seq % 2 == 0 and seq > last_seq:
                if length == STOP:
                    raise ChannelClosed(self.path)
                payload = bytes(self._view[_HEADER.size : _HEADER.size + length])
                seq2, _ = _HEADER.unpack_from(self._view, 0)
                if seq2 == seq:
                    return payload, seq
                continue  # torn read: writer advanced mid-copy
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.path} idle past {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.001)

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
            os.close(self._fd)
        except Exception:
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------- cross-node
# TCP mutable channels with the same latest-wins/seq semantics as the shm
# channel, for DAG edges whose endpoints live on different nodes (reference
# ``experimental/channel/shared_memory_channel.py`` falls back to its
# cross-node transport the same way). Frame: [u64 seq][u32 len][payload];
# len == STOP_LEN signals writer close.

import socket
import struct as _struct
import threading

_FRAME = _struct.Struct("<QI")
_REQ = _struct.Struct("<Q")
STOP_LEN = 0xFFFFFFFF


class TcpChannelServer:
    """Writer end: holds the latest message; any number of readers long-
    poll for sequences newer than their cursor."""

    def __init__(self, host: str = "0.0.0.0", advertise: str | None = None):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        self.address = f"{advertise or '127.0.0.1'}:{port}"
        self._cond = threading.Condition()
        self._seq = 0
        self._payload = b""
        self._stopped = False
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # writer interface (mirrors Channel)
    def write(self, payload: bytes) -> None:
        with self._cond:
            self._seq += 1
            self._payload = bytes(payload)
            self._cond.notify_all()

    def close_writer(self) -> None:
        with self._cond:
            self._stopped = True
            self._seq += 1
            self._cond.notify_all()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ internals
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_exact(conn, _REQ.size)
                if req is None:
                    return
                (last_seq,) = _REQ.unpack(req)
                with self._cond:
                    while self._seq <= last_seq and not self._stopped:
                        self._cond.wait(1.0)
                        if self._closed:
                            return
                    # Same semantics as the shm channel: close_writer
                    # overrides the slot — once stopped, readers see STOP.
                    if self._stopped:
                        conn.sendall(_FRAME.pack(self._seq, STOP_LEN))
                        continue
                    seq, payload = self._seq, self._payload
                conn.sendall(_FRAME.pack(seq, len(payload)) + payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class TcpChannelReader:
    """Reader end: same interface as Channel.read (blocking, cursor-based)."""

    def __init__(self, address: str, capacity: int = 0, connect_timeout: float = 30.0):
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)

    def read(self, last_seq: int, timeout: float | None = None) -> tuple[bytes, int]:
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(_REQ.pack(last_seq))
            head = _recv_exact(self._sock, _FRAME.size)
            if head is None:
                raise ChannelClosed("tcp channel writer gone")
            seq, length = _FRAME.unpack(head)
            if length == STOP_LEN:
                raise ChannelClosed("tcp channel stopped")
            payload = _recv_exact(self._sock, length)
            if payload is None:
                raise ChannelClosed("tcp channel writer gone")
            return payload, seq
        except socket.timeout:
            raise TimeoutError(f"tcp channel idle past {timeout}s")
        finally:
            self._sock.settimeout(None)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
