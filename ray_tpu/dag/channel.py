"""Mutable single-writer channel over an mmap'd /dev/shm file.

Equivalent of the reference's mutable-object channels
(``src/ray/core_worker/experimental_mutable_object_manager.h``): a
fixed-capacity buffer a writer overwrites in place, readers follow a
sequence counter. Layout:

    [u64 seq][u64 len][payload ... capacity]

``seq`` is odd WHILE a write is in progress (seqlock): readers that see
an odd seq, or whose second seq read differs from the first, retry — so
a torn read is impossible without any cross-process lock. A ``len`` of
``STOP`` tears the channel down (executor loops exit).
"""

from __future__ import annotations

import mmap
import os
import struct
import time

_HEADER = struct.Struct("<QQ")
STOP = 0xFFFFFFFFFFFFFFFF


class ChannelClosed(Exception):
    pass


class Channel:
    def __init__(self, path: str, capacity: int, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HEADER.size + capacity
        if create:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            os.ftruncate(fd, total)
        else:
            fd = os.open(path, os.O_RDWR)
        self._fd = fd
        self._mm = mmap.mmap(fd, total)
        self._view = memoryview(self._mm)

    # ------------------------------------------------------------------ write
    def write(self, payload: bytes) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity} (raise max_buffer_size at compile time)"
            )
        seq, _ = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, len(payload))  # odd: in progress
        self._view[_HEADER.size : _HEADER.size + len(payload)] = payload
        _HEADER.pack_into(self._view, 0, seq + 2, len(payload))  # even: committed

    def close_writer(self) -> None:
        # Two-phase, but the STOP length lands while seq is still ODD and
        # the commit touches ONLY the seq word: a torn header can therefore
        # never pair the new even seq with the stale length (which would
        # re-consume the final payload and skip the STOP forever). write()
        # is safe with its wider commit because its odd phase pre-writes
        # the same length the commit re-writes.
        seq, _length = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, STOP)  # odd: STOP staged
        struct.pack_into("<Q", self._view, 0, seq + 2)   # commit seq alone

    # ------------------------------------------------------------------- read
    def read(self, last_seq: int, timeout: float | None = None) -> tuple[bytes, int]:
        """Block (spin) until a version newer than ``last_seq`` commits;
        returns (payload, seq). Raises ChannelClosed on teardown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 1e-5
        while True:
            seq, length = _HEADER.unpack_from(self._view, 0)
            if seq % 2 == 0 and seq > last_seq:
                if length == STOP:
                    raise ChannelClosed(self.path)
                payload = bytes(self._view[_HEADER.size : _HEADER.size + length])
                seq2, _ = _HEADER.unpack_from(self._view, 0)
                if seq2 == seq:
                    return payload, seq
                continue  # torn read: writer advanced mid-copy
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.path} idle past {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.001)

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
            os.close(self._fd)
        except Exception:
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
