"""Mutable single-writer channel over an mmap'd /dev/shm file.

Equivalent of the reference's mutable-object channels
(``src/ray/core_worker/experimental_mutable_object_manager.h``): a
fixed-capacity buffer a writer overwrites in place, readers follow a
sequence counter. Layout:

    [u64 seq][u64 len][payload ... capacity]

``seq`` is odd WHILE a write is in progress (seqlock): readers that see
an odd seq, or whose second seq read differs from the first, retry — so
a torn read is impossible without any cross-process lock. A ``len`` of
``STOP`` tears the channel down (executor loops exit).
"""

from __future__ import annotations

import mmap
import os
import struct
import time

_HEADER = struct.Struct("<QQ")
STOP = 0xFFFFFFFFFFFFFFFF


class ChannelClosed(Exception):
    pass


class Channel:
    def __init__(self, path: str, capacity: int, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HEADER.size + capacity
        if create:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            os.ftruncate(fd, total)
        else:
            fd = os.open(path, os.O_RDWR)
        self._fd = fd
        self._mm = mmap.mmap(fd, total)
        self._view = memoryview(self._mm)

    # ------------------------------------------------------------------ write
    def write(self, payload: bytes) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity} (raise max_buffer_size at compile time)"
            )
        seq, _ = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, len(payload))  # odd: in progress
        self._view[_HEADER.size : _HEADER.size + len(payload)] = payload
        _HEADER.pack_into(self._view, 0, seq + 2, len(payload))  # even: committed

    def close_writer(self) -> None:
        # Two-phase, but the STOP length lands while seq is still ODD and
        # the commit touches ONLY the seq word: a torn header can therefore
        # never pair the new even seq with the stale length (which would
        # re-consume the final payload and skip the STOP forever). write()
        # is safe with its wider commit because its odd phase pre-writes
        # the same length the commit re-writes.
        seq, _length = _HEADER.unpack_from(self._view, 0)
        _HEADER.pack_into(self._view, 0, seq + 1, STOP)  # odd: STOP staged
        struct.pack_into("<Q", self._view, 0, seq + 2)   # commit seq alone

    # ------------------------------------------------------------------- read
    def read(self, last_seq: int, timeout: float | None = None) -> tuple[bytes, int]:
        """Block (spin) until a version newer than ``last_seq`` commits;
        returns (payload, seq). Raises ChannelClosed on teardown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 1e-5
        while True:
            seq, length = _HEADER.unpack_from(self._view, 0)
            if seq % 2 == 0 and seq > last_seq:
                if length == STOP:
                    raise ChannelClosed(self.path)
                payload = bytes(self._view[_HEADER.size : _HEADER.size + length])
                seq2, _ = _HEADER.unpack_from(self._view, 0)
                if seq2 == seq:
                    return payload, seq
                continue  # torn read: writer advanced mid-copy
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.path} idle past {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.001)

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
            os.close(self._fd)
        except Exception:
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ------------------------------------------------------------- ring channel
# Credit-based STREAMING channel for compiled loops (dag/loop.py): unlike
# the latest-wins mutable Channel above, every message is delivered
# exactly once per reader, and the writer blocks once it runs
# ``n_slots`` messages ahead of the slowest reader — backpressure
# propagates hop by hop through a pipeline without any control RPCs
# (the reference's bounded-buffer compiled-graph channels). Layout:
#
#     [u64 write_seq][u64 n_readers][u64 cursor * n_readers]
#     [slot 0: u64 len + payload] ... [slot n_slots-1]
#
# Slot ``s`` holds message ``seq`` iff ``seq % n_slots == s``. A slot is
# only rewritten after every reader's cursor has passed it (the credit
# protocol), so no seqlock is needed: the writer fills the payload, then
# publishes by bumping ``write_seq``. A ``len`` of STOP closes the
# channel; readers drain every message queued before it, then raise
# ChannelClosed forever after (close-after-drain semantics — loop
# teardown lets in-flight iterations finish).

_RING_HEAD = struct.Struct("<QQ")
_SLOT_HEAD = struct.Struct("<Q")


class RingChannel:
    """Single-writer multi-reader bounded ring over an mmap'd shm file.

    One process opens the writer end (``reader_index=None``); each
    consumer opens a reader end with its compile-assigned
    ``reader_index`` in ``[0, n_readers)``. ``write`` blocks while the
    ring is full (slowest reader more than ``n_slots`` behind).
    """

    def __init__(self, path: str, slot_size: int, n_slots: int,
                 n_readers: int = 1, create: bool = False,
                 reader_index: int | None = None):
        self.path = path
        self.slot_size = slot_size
        self.n_slots = n_slots
        self.reader_index = reader_index
        if create:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        else:
            # The reader-cursor table sizes the layout: always take the
            # authoritative count from the creator's header.
            fd = os.open(path, os.O_RDWR)
            n_readers = _RING_HEAD.unpack(os.pread(fd, _RING_HEAD.size, 0))[1]
        self.n_readers = n_readers
        self._cursor_off = _RING_HEAD.size
        self._slots_off = _RING_HEAD.size + 8 * n_readers
        total = self._slots_off + n_slots * (_SLOT_HEAD.size + slot_size)
        if create:
            os.ftruncate(fd, total)
        self._fd = fd
        self._mm = mmap.mmap(fd, total)
        self._view = memoryview(self._mm)
        if create:
            _RING_HEAD.pack_into(self._view, 0, 0, n_readers)

    # ------------------------------------------------------------ internals
    def _write_seq(self) -> int:
        return _RING_HEAD.unpack_from(self._view, 0)[0]

    def _cursor(self, r: int) -> int:
        return struct.unpack_from("<Q", self._view, self._cursor_off + 8 * r)[0]

    def _min_cursor(self) -> int:
        return min(self._cursor(r) for r in range(self.n_readers))

    def _slot(self, seq: int) -> int:
        return self._slots_off + (seq % self.n_slots) * (
            _SLOT_HEAD.size + self.slot_size)

    def occupancy(self) -> int:
        """Messages written but not yet consumed by the slowest reader —
        the channel-fill gauge the loop runtime exports."""
        return self._write_seq() - self._min_cursor()

    # ------------------------------------------------------------------ write
    def write(self, payload: bytes, timeout: float | None = None) -> None:
        if len(payload) > self.slot_size:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds ring slot size "
                f"{self.slot_size} (raise max_buffer_size at compile time)")
        seq = self._wait_for_credit(timeout)
        off = self._slot(seq)
        _SLOT_HEAD.pack_into(self._view, off, len(payload))
        self._view[off + _SLOT_HEAD.size:
                   off + _SLOT_HEAD.size + len(payload)] = payload
        _RING_HEAD.pack_into(self._view, 0, seq + 1, self.n_readers)

    def _wait_for_credit(self, timeout: float | None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 1e-5
        while True:
            seq = self._write_seq()
            if seq - self._min_cursor() < self.n_slots:
                return seq
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"ring {self.path} full past {timeout}s (no reader credit)")
            time.sleep(delay)
            delay = min(delay * 2, 0.001)

    def close_writer(self, timeout: float | None = 30.0) -> None:
        """Queue a STOP after everything already written (close-after-
        drain). Falls back to ``force_close`` if readers never free a
        slot within ``timeout`` (dead consumer)."""
        try:
            seq = self._wait_for_credit(timeout)
        except TimeoutError:
            self.force_close()
            return
        _SLOT_HEAD.pack_into(self._view, self._slot(seq), STOP)
        _RING_HEAD.pack_into(self._view, 0, seq + 1, self.n_readers)

    def force_close(self) -> None:
        """Overwrite the OLDEST unconsumed slot with STOP, ignoring
        credits. Loses queued messages — teardown-after-failure only
        (e.g. the writing stage died and the driver unblocks its
        consumers)."""
        seq = max(self._min_cursor(), self._write_seq() - self.n_slots + 1)
        _SLOT_HEAD.pack_into(self._view, self._slot(seq), STOP)
        if self._write_seq() <= seq:
            _RING_HEAD.pack_into(self._view, 0, seq + 1, self.n_readers)

    # ------------------------------------------------------------------- read
    def read(self, timeout: float | None = None) -> bytes:
        """Next message for this reader end (exactly-once, in order).
        Consuming it releases the slot back to the writer (the credit)."""
        r = self.reader_index
        if r is None:
            raise RuntimeError("this end of the ring is the writer")
        cur = self._cursor(r)
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 1e-5
        while self._write_seq() <= cur:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"ring {self.path} idle past {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.001)
        off = self._slot(cur)
        (length,) = _SLOT_HEAD.unpack_from(self._view, off)
        if length == STOP:
            raise ChannelClosed(self.path)  # cursor stays: STOP is sticky
        payload = bytes(self._view[off + _SLOT_HEAD.size:
                                   off + _SLOT_HEAD.size + length])
        struct.pack_into("<Q", self._view, self._cursor_off + 8 * r, cur + 1)
        return payload

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
            os.close(self._fd)
        except Exception:
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------- cross-node
# TCP mutable channels with the same latest-wins/seq semantics as the shm
# channel, for DAG edges whose endpoints live on different nodes (reference
# ``experimental/channel/shared_memory_channel.py`` falls back to its
# cross-node transport the same way). Frame: [u64 seq][u32 len][payload];
# len == STOP_LEN signals writer close.

import socket
import struct as _struct
import threading

_FRAME = _struct.Struct("<QI")
_REQ = _struct.Struct("<Q")
STOP_LEN = 0xFFFFFFFF


class TcpChannelServer:
    """Writer end: holds the latest message; any number of readers long-
    poll for sequences newer than their cursor."""

    def __init__(self, host: str = "0.0.0.0", advertise: str | None = None):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        self.address = f"{advertise or '127.0.0.1'}:{port}"
        self._cond = threading.Condition()
        self._seq = 0
        self._payload = b""
        self._stopped = False
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # writer interface (mirrors Channel)
    def write(self, payload: bytes) -> None:
        with self._cond:
            self._seq += 1
            self._payload = bytes(payload)
            self._cond.notify_all()

    def close_writer(self) -> None:
        with self._cond:
            self._stopped = True
            self._seq += 1
            self._cond.notify_all()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ internals
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_exact(conn, _REQ.size)
                if req is None:
                    return
                (last_seq,) = _REQ.unpack(req)
                with self._cond:
                    while self._seq <= last_seq and not self._stopped:
                        self._cond.wait(1.0)
                        if self._closed:
                            return
                    # Same semantics as the shm channel: close_writer
                    # overrides the slot — once stopped, readers see STOP.
                    if self._stopped:
                        conn.sendall(_FRAME.pack(self._seq, STOP_LEN))
                        continue
                    seq, payload = self._seq, self._payload
                conn.sendall(_FRAME.pack(seq, len(payload)) + payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class TcpChannelReader:
    """Reader end: same interface as Channel.read (blocking, cursor-based)."""

    def __init__(self, address: str, capacity: int = 0, connect_timeout: float = 30.0):
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)

    def read(self, last_seq: int, timeout: float | None = None) -> tuple[bytes, int]:
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(_REQ.pack(last_seq))
            head = _recv_exact(self._sock, _FRAME.size)
            if head is None:
                raise ChannelClosed("tcp channel writer gone")
            seq, length = _FRAME.unpack(head)
            if length == STOP_LEN:
                raise ChannelClosed("tcp channel stopped")
            payload = _recv_exact(self._sock, length)
            if payload is None:
                raise ChannelClosed("tcp channel writer gone")
            return payload, seq
        except socket.timeout:
            raise TimeoutError(f"tcp channel idle past {timeout}s")
        finally:
            self._sock.settimeout(None)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ----------------------------------------------------------- cross-node loop
# Streaming (exactly-once, credit-bounded) TCP channel for compiled-loop
# edges whose endpoints live on different nodes: the server buffers the
# last ``n_slots`` messages and ``write`` blocks until the slowest of the
# ``n_readers`` expected readers has consumed far enough — the TCP
# equivalent of RingChannel, same close-after-drain STOP semantics.

class TcpLoopServer:
    """Writer end of a cross-node loop channel."""

    def __init__(self, n_slots: int, n_readers: int = 1,
                 host: str = "0.0.0.0", advertise: str | None = None):
        self.n_slots = n_slots
        self.n_readers = n_readers
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        self.address = f"{advertise or '127.0.0.1'}:{port}"
        self._cond = threading.Condition()
        self._seq = 0                      # messages written so far
        self._buffer: dict[int, bytes] = {}  # seq -> payload (last n_slots)
        self._acked: dict[int, int] = {}   # conn id -> messages consumed
        self._stopped = False
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _min_acked(self) -> int:
        # Readers that have not connected yet count as cursor 0 — the
        # writer can run at most n_slots ahead of a late joiner.
        acked = list(self._acked.values())
        while len(acked) < self.n_readers:
            acked.append(0)
        return min(acked)

    def occupancy(self) -> int:
        with self._cond:
            return self._seq - self._min_acked()

    def write(self, payload: bytes, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._seq - self._min_acked() >= self.n_slots:
                if self._closed:
                    raise ChannelClosed(self.address)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"loop channel {self.address} full past {timeout}s")
                self._cond.wait(0.05)
            self._buffer[self._seq] = bytes(payload)
            self._seq += 1
            self._buffer.pop(self._seq - self.n_slots - 1, None)
            self._cond.notify_all()

    def close_writer(self, timeout: float | None = None) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    force_close = close_writer  # queued messages still drain; then STOP

    def close(self) -> None:
        self._closed = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        cid = id(conn)
        try:
            while True:
                req = _recv_exact(conn, _REQ.size)
                if req is None:
                    return
                (cursor,) = _REQ.unpack(req)  # messages consumed so far
                with self._cond:
                    self._acked[cid] = max(self._acked.get(cid, 0), cursor)
                    self._cond.notify_all()
                    while self._seq <= cursor and not self._stopped:
                        self._cond.wait(1.0)
                        if self._closed:
                            return
                    if self._seq <= cursor and self._stopped:
                        conn.sendall(_FRAME.pack(cursor, STOP_LEN))
                        continue
                    payload = self._buffer.get(cursor)
                if payload is None:
                    # Reader fell behind the buffer window (only possible
                    # after a force_close raced it): surface as closed.
                    conn.sendall(_FRAME.pack(cursor, STOP_LEN))
                    continue
                conn.sendall(_FRAME.pack(cursor, len(payload)) + payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class TcpLoopReader:
    """Reader end: blocking, exactly-once, in-order (mirrors
    RingChannel.read)."""

    def __init__(self, address: str, connect_timeout: float = 30.0):
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._cursor = 0

    def read(self, timeout: float | None = None) -> bytes:
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(_REQ.pack(self._cursor))
            head = _recv_exact(self._sock, _FRAME.size)
            if head is None:
                raise ChannelClosed("loop channel writer gone")
            _seq, length = _FRAME.unpack(head)
            if length == STOP_LEN:
                raise ChannelClosed("loop channel stopped")
            payload = _recv_exact(self._sock, length)
            if payload is None:
                raise ChannelClosed("loop channel writer gone")
            self._cursor += 1
            return payload
        except socket.timeout:
            raise TimeoutError(f"loop channel idle past {timeout}s")
        finally:
            self._sock.settimeout(None)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
