"""Persistent compiled loops: the streaming sibling of ``CompiledDAG``.

``CompiledDAG.execute()`` is one-shot — each call pushes ONE input and
synchronously drains ONE output round. A steady-state iteration loop
(the pp inference engine's decode tick path, a training step loop) wants
the other half of the reference's compiled-graph design: pre-negotiate
resources ONCE, then stream iterations over dedicated channels with NO
per-tick task submission, RPC, or lease traffic at all.

``compile_loop(graph)`` installs a never-returning tick executor on each
stage actor (one ``__ray_call__`` submission per stage — the only task
the loop ever submits), wires the stages with credit-based streaming
channels (``RingChannel`` shm rings node-locally, ``TcpLoopServer``
across nodes), and returns a :class:`CompiledLoop`:

  * ``loop.put(x)`` enqueues an iteration input; it blocks only when the
    pipeline is ``credits`` iterations deep (backpressure propagates hop
    by hop through the ring credits — no control RPCs).
  * ``loop.get()`` returns the next iteration's output(s), in order,
    exactly once. ``put``/``get`` may run from different threads;
    ``run(x)`` is the synchronous convenience.
  * ``loop.teardown()`` closes the input ring; ``ChannelClosed`` cascades
    stage to stage exactly like the one-shot DAG — in-flight iterations
    drain first (close-after-drain STOP semantics).

Differences from the one-shot DAG worth knowing:

  * Channels DELIVER EVERY MESSAGE (bounded ring), not latest-wins — an
    iteration can never be overwritten by the next one.
  * Stage errors serialize through the pipe per iteration: the loop
    survives, the failing iteration's ``get()`` re-raises.
  * Stage workers are LEASE-PINNED: the raylet is told these workers
    park a resident loop, so the chaos orphan-lease watchdog never
    reclaims them as stranded grants (``PinLoopWorker``).
  * Observability: every stage counts ``ray_tpu_dag_loop_ticks_total``
    and gauges its output-channel occupancy; one ``dag.loop.tick`` span
    per ``dag_loop_span_every`` ticks rides the normal span flush.
"""

from __future__ import annotations

import os
import tempfile
import time
import uuid

from .channel import (ChannelClosed, RingChannel, TcpLoopReader,
                      TcpLoopServer)
from .compiled import _pack, _pack_error, _probe_node, _routable_host, _unpack
from .nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode


def _open_loop_reader(spec):
    """Open the reader end of an input spec ("ring", path, slots, readers,
    index) or ("tcp", address)."""
    if spec[0] == "tcp":
        return TcpLoopReader(spec[1])
    _, path, slot_size, n_slots, _n_readers, index = spec
    return RingChannel(path, slot_size, n_slots, reader_index=index)


def _create_loop_out_server(instance, n_slots: int, n_readers: int) -> str:
    """Phase-1 for a cross-node loop producer: create the streaming TCP
    server in the actor process and return its address."""
    server = TcpLoopServer(n_slots, n_readers, advertise=_routable_host())
    instance.__dict__["_dag_loop_out_server"] = server
    return server.address


_tick_metrics = None


def _loop_metrics():
    """Per-process loop metrics, created lazily so loop-free processes
    never start the metrics flusher."""
    global _tick_metrics
    if _tick_metrics is None:
        from ..util.metrics import Counter, Gauge

        _tick_metrics = (
            Counter("ray_tpu_dag_loop_ticks_total",
                    "Iterations executed by resident compiled-loop stages",
                    tag_keys=("loop", "stage")),
            Gauge("ray_tpu_dag_loop_channel_occupancy",
                  "Unconsumed iterations queued in a loop stage's output "
                  "channel (0..credits; credits = backpressure engaged)",
                  tag_keys=("loop", "stage")),
        )
    return _tick_metrics


def _loop_tick(instance, method_name: str, in_specs: list, out_desc,
               loop_id: str, span_every: int) -> str:
    """The resident tick executor (ships to the stage actor via
    ``__ray_call__`` and never returns until teardown): read one
    iteration's inputs, apply the bound method, stream the result out.
    Blocking anywhere in the channel protocol IS the backpressure."""
    from ..core.rpc import get_chaos

    readers = {i: _open_loop_reader(spec) for i, (kind, spec)
               in enumerate(in_specs) if kind == "chan"}
    if out_desc[0] == "tcp":
        out = instance.__dict__.pop("_dag_loop_out_server")
    else:
        _, path, slot_size, n_slots, n_readers = out_desc
        out = RingChannel(path, slot_size, n_slots)
        with open(path + ".ready", "w") as f:
            f.write("1")  # compile blocks on this marker (see _wait_ready)
    method = getattr(instance, method_name)
    ticks = 0
    counter, occupancy = _loop_metrics()
    tags = {"loop": loop_id, "stage": method_name}
    try:
        while True:
            args, upstream_error = [], None
            for i, (kind, spec) in enumerate(in_specs):
                if kind == "const":
                    args.append(spec)
                    continue
                value, is_error = _unpack(readers[i].read())
                if is_error and upstream_error is None:
                    upstream_error = value
                args.append(value)
            if get_chaos().take_kill_loop_tick():
                # Deterministic chaos: this stage dies mid-loop, exactly
                # between consuming its inputs and producing its output.
                os._exit(1)
            if upstream_error is not None:
                out.write(_pack_error(upstream_error))
                ticks += 1
                continue
            t0 = time.time()
            try:
                result = method(*args)
                payload = _pack(result)  # inside try: unpicklable results
            except Exception as e:
                import traceback

                from ..core.status import RayTaskError

                payload = _pack_error(
                    RayTaskError(method_name, traceback.format_exc(), e))
            out.write(payload)
            ticks += 1
            counter.inc(tags=tags)
            occupancy.set(out.occupancy(), tags=tags)
            if span_every and ticks % span_every == 0:
                from ..observability import tracing

                tracing.record_span(tracing.make_span(
                    "dag.loop.tick", "dag", t0, time.time(), loop_id,
                    attrs={"stage": method_name, "tick": ticks,
                           "out_occupancy": out.occupancy()}))
    except ChannelClosed:
        out.close_writer()  # cascade teardown downstream
        return "closed"
    finally:
        for r in readers.values():
            r.close()
        out.close()


class CompiledLoop:
    """A compiled, resident iteration pipeline over stage actors.

    Build with :func:`compile_loop` (or
    ``node.experimental_compile_loop()``). One ``put`` produces exactly
    one ``get``-able output round; rounds stream in order with at most
    ``credits`` iterations in flight.
    """

    def __init__(self, output_node: DAGNode, max_buffer_size: int | None = None,
                 credits: int | None = None):
        from ..core import api as ray
        from ..core.config import get_config

        cfg = get_config()
        self.capacity = max_buffer_size or cfg.dag_channel_capacity
        self.credits = max(2, credits or cfg.dag_loop_credits)
        self._span_every = cfg.dag_loop_span_every
        self._dir: str | None = None
        self._input_node: InputNode | None = None
        self._outputs: list[ClassMethodNode] = []
        self._loop_refs = []
        self._torn_down = False
        self._broken: str | None = None
        self._puts = 0
        self._gets = 0
        self._resume: list | None = None  # partial round after a get timeout
        from ..observability import tracing

        self.loop_id = tracing.new_trace_id()

        if isinstance(output_node, MultiOutputNode):
            self._outputs = list(output_node.outputs)
        else:
            self._outputs = [output_node]
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise TypeError("loop outputs must be actor method nodes")
        if len({id(o) for o in self._outputs}) != len(self._outputs):
            raise ValueError("a node may appear only once in a loop's "
                             "outputs (duplicates would alias ring cursors)")

        order = self._toposort()
        if self._input_node is None:
            raise ValueError("a compiled loop needs an InputNode")
        seen_actors: dict[bytes, str] = {}
        self._stage_nodes: list[ClassMethodNode] = []
        for node in order:
            if not isinstance(node, ClassMethodNode):
                continue
            if hasattr(node, "materialize_actor"):
                node.materialize_actor()
            actor_id = node.actor._actor_id
            if actor_id in seen_actors:
                raise ValueError(
                    f"actor used by both '{seen_actors[actor_id]}' and "
                    f"'{node.method_name}' — a compiled loop supports one "
                    "node per actor (create a separate actor per stage)")
            seen_actors[actor_id] = node.method_name
            self._stage_nodes.append(node)

        # Consumers per producer, in deterministic order; one reader end
        # per (consumer, arg position) so a node consuming the same
        # upstream twice gets two independent cursors. The driver is the
        # final consumer of every output node.
        consumers: dict[int, list] = {id(n): [] for n in order}
        for node in order:
            if isinstance(node, ClassMethodNode):
                for pos, arg in enumerate(node.args):
                    if isinstance(arg, DAGNode):
                        consumers[id(arg)].append((node, pos))
        for out in self._outputs:
            consumers[id(out)].append(("driver", 0))

        driver_node = ray.get_runtime_context().node_id
        node_of: dict[int, str] = {id(self._input_node): driver_node}
        for node in self._stage_nodes:
            node_of[id(node)] = ray.get(
                node.actor.__ray_call__.remote(_probe_node), timeout=60)

        self._dir = tempfile.mkdtemp(prefix="raytpu_dag_", dir="/dev/shm")
        # Producer -> writer descriptor + per-consumer reader specs.
        self._out_desc: dict[int, tuple] = {}
        self._reader_spec: dict[tuple, tuple] = {}  # (prod id, consumer idx)
        self._ring_paths: list[str] = []
        self._input_server = None
        for node in order:
            cons = consumers[id(node)]
            if not cons:
                continue
            n_readers = len(cons)
            # shm ring when every endpoint (producer + all consumers,
            # driver included) shares a node; streaming TCP otherwise.
            local = all(
                (driver_node if c[0] == "driver" else node_of[id(c[0])])
                == node_of[id(node)] for c in cons)
            if local:
                path = os.path.join(self._dir, f"lp_{uuid.uuid4().hex[:10]}")
                RingChannel(path, self.capacity, self.credits,
                            n_readers=n_readers, create=True).close()
                self._ring_paths.append(path)
                self._out_desc[id(node)] = (
                    "ring", path, self.capacity, self.credits, n_readers)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = (
                        "ring", path, self.capacity, self.credits,
                        n_readers, idx)
            elif node is self._input_node:
                self._input_server = TcpLoopServer(
                    self.credits, n_readers, advertise=_routable_host())
                self._out_desc[id(node)] = ("tcp", self._input_server.address)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = (
                        "tcp", self._input_server.address)
            else:
                addr = ray.get(node.actor.__ray_call__.remote(
                    _create_loop_out_server, self.credits, n_readers),
                    timeout=60)
                self._out_desc[id(node)] = ("tcp", addr)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = ("tcp", addr)

        # Driver ends: the input writer + one reader per output node.
        in_desc = self._out_desc[id(self._input_node)]
        if in_desc[0] == "tcp":
            self._input = self._input_server
        else:
            self._input = RingChannel(in_desc[1], self.capacity, self.credits)
        self._out_readers = []
        for node in self._outputs:
            idx = consumers[id(node)].index(("driver", 0))
            self._out_readers.append(
                _open_loop_reader(self._reader_spec[(id(node), idx)]))

        # Install the resident tick executors, upstream-last so consumers
        # are listening before producers can emit.
        self._actors = []
        self._actor_nodes: list[tuple[str, str]] = []  # (actor hex, node id)
        for node in self._stage_nodes:
            self._actor_nodes.append(
                (node.actor._actor_id.hex(), node_of[id(node)]))
            in_specs = []
            for pos, arg in enumerate(node.args):
                if isinstance(arg, DAGNode):
                    idx = consumers[id(arg)].index((node, pos))
                    in_specs.append(
                        ("chan", self._reader_spec[(id(arg), idx)]))
                else:
                    in_specs.append(("const", arg))
            ref = node.actor.__ray_call__.remote(
                _loop_tick, node.method_name, in_specs,
                self._out_desc[id(node)], self.loop_id, self._span_every)
            self._loop_refs.append(ref)
            self._actors.append(node.actor)
        self._wait_ready(timeout=cfg.dag_ready_timeout_s)
        # Lease-pin the stage workers: these actors now park a resident
        # loop task, and the orphan-lease watchdog must not mistake the
        # (idle-looking, never-returning) lease for a stranded grant.
        self._pinned = self._pin_workers(True)

    # ------------------------------------------------------------- plumbing
    def _toposort(self) -> list[DAGNode]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(node: DAGNode) -> None:
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, InputNode):
                if self._input_node is not None and self._input_node is not node:
                    raise ValueError("a compiled loop supports one InputNode")
                self._input_node = node
                order.append(node)
                return
            if isinstance(node, ClassMethodNode):
                if not node.upstream():
                    raise ValueError(
                        f"{node.method_name}.bind(...) has no upstream node — "
                        "a loop stage needs at least one DAG input")
                for up in node.upstream():
                    visit(up)
                order.append(node)
                return
            raise TypeError(f"unsupported DAG node {type(node).__name__}")

        for out in self._outputs:
            visit(out)
        return order

    def _wait_ready(self, timeout: float) -> None:
        from ..core import api as ray

        markers = [desc[1] + ".ready"
                   for nid, desc in self._out_desc.items()
                   if desc[0] == "ring" and nid != id(self._input_node)]
        deadline = time.monotonic() + timeout
        while True:
            if all(os.path.exists(m) for m in markers):
                return
            done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
            if done:
                ray.get(done[0])
                raise RuntimeError("loop executor exited during compile")
            if time.monotonic() > deadline:
                missing = [m for m in markers if not os.path.exists(m)]
                raise TimeoutError(
                    f"{len(missing)} loop executor(s) not ready after "
                    f"{timeout}s: {missing[:3]}")
            time.sleep(0.01)

    def _pin_workers(self, pinned: bool) -> bool:
        try:
            from ..core.worker import global_worker

            w = global_worker()
            for actor_hex, node_id in self._actor_nodes:
                w.pin_loop_worker(actor_hex, pinned, node_id=node_id)
            return pinned
        except Exception:
            return False  # pinning is protective, never fatal

    def _check_stage_death(self) -> None:
        """A completed loop ref at steady state means its stage DIED (or
        its install failed): surface the real error and break the loop."""
        from ..core import api as ray

        done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
        if not done:
            return
        try:
            result = ray.get(done[0])
            if result == "closed":
                return  # normal cascade exit, not a death
            err: Exception = ChannelClosed(f"loop stage exited: {result!r}")
        except Exception as e:
            err = e
        self._break(f"stage died: {err}")
        raise err

    def _break(self, reason: str) -> None:
        """Force-teardown after a failure: unblock every parked stage by
        force-closing the shm rings (a dead stage's consumers would
        otherwise spin forever on a channel nobody will ever close)."""
        if self._broken is not None:
            return
        self._broken = reason
        if self._input is not None:
            self._input.force_close()
        for path in self._ring_paths:
            try:
                RingChannel(path, self.capacity, self.credits).force_close()
            except OSError:
                pass
        self._pin_workers(False)

    # ------------------------------------------------------------------- API
    @property
    def in_flight(self) -> int:
        """Iterations put but not yet fully consumed by ``get``."""
        return self._puts - self._gets

    def put(self, value, timeout: float | None = 60.0) -> None:
        """Enqueue one iteration input. Blocks only when the pipeline
        already holds ``credits`` unconsumed iterations (backpressure)."""
        if self._torn_down or self._broken:
            raise ChannelClosed(self._broken or "loop torn down")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                self._input.write(_pack(value), timeout=0.25)
                self._puts += 1
                return
            except TimeoutError:
                self._check_stage_death()
                if deadline is not None and time.monotonic() > deadline:
                    raise

    def get(self, timeout: float | None = 60.0):
        """Next iteration's output (tuple for MultiOutputNode), in put
        order. Re-raises a stage's per-iteration error; the loop itself
        survives errors and keeps streaming."""
        if self._torn_down:
            raise ChannelClosed("loop torn down")
        if self._broken and self._resume is None:
            raise ChannelClosed(self._broken)
        deadline = None if timeout is None else time.monotonic() + timeout
        # Resume a round a previous timed-out get left half-read, so
        # output cursors never desync across rounds.
        results = self._resume if self._resume is not None else []
        self._resume = None
        first_error = None
        while len(results) < len(self._out_readers):
            reader = self._out_readers[len(results)]
            try:
                payload = reader.read(timeout=0.25)
            except TimeoutError:
                # Slicing the wait keeps stage-death detection prompt; a
                # transient timeout here is NOT a failed round yet.
                self._check_stage_death()
                if deadline is not None and time.monotonic() > deadline:
                    # Preserve the half-read round so the next get()
                    # resumes at the SAME reader — cursors never desync.
                    self._resume = results
                    raise TimeoutError(
                        f"loop output idle past {timeout}s "
                        f"({self.in_flight} iterations in flight)")
                continue
            except ChannelClosed:
                self._break("loop output channel closed")
                raise
            results.append(_unpack(payload))
        self._gets += 1
        values = []
        for value, is_error in results:
            if is_error and first_error is None:
                first_error = value
            values.append(value)
        if first_error is not None:
            from ..core.status import RayTaskError

            raise (first_error.as_instanceof_cause()
                   if isinstance(first_error, RayTaskError) else first_error)
        return values[0] if len(values) == 1 else tuple(values)

    def run(self, value, timeout: float | None = 60.0):
        """Synchronous convenience: one put + one get."""
        self.put(value, timeout=timeout)
        return self.get(timeout=timeout)

    # --------------------------------------------------------------- teardown
    def teardown(self, timeout: float = 30.0) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        from ..chaos import clock as chaos_clock
        from ..core import api as ray

        t0 = chaos_clock.now()
        input_ch = getattr(self, "_input", None)
        if input_ch is not None and self._broken is None:
            input_ch.close_writer(timeout=min(timeout, 5.0))
        try:
            ray.get(list(self._loop_refs), timeout=timeout)
        except Exception:
            # A stage died or is stuck on a dead peer's channel: force
            # the cascade through every ring so the rest exit.
            self._break("teardown")
            try:
                ray.get(list(self._loop_refs), timeout=timeout)
            except Exception:
                pass
        self._pin_workers(False)
        if input_ch is not None:
            input_ch.close()
        for r in getattr(self, "_out_readers", []):
            r.close()
        if self._dir is not None:
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)
        self.torn_down_in_s = chaos_clock.now() - t0

    def __del__(self):
        try:
            self.teardown(timeout=1.0)
        except Exception:
            pass


def compile_loop(output_node: DAGNode, max_buffer_size: int | None = None,
                 credits: int | None = None) -> CompiledLoop:
    """Compile a DAG built with ``actor.method.bind(...)`` into a
    persistent streaming loop (see module docstring)."""
    return CompiledLoop(output_node, max_buffer_size=max_buffer_size,
                        credits=credits)
