"""Persistent compiled loops: the streaming sibling of ``CompiledDAG``.

``CompiledDAG.execute()`` is one-shot — each call pushes ONE input and
synchronously drains ONE output round. A steady-state iteration loop
(the pp inference engine's decode tick path, a training step loop) wants
the other half of the reference's compiled-graph design: pre-negotiate
resources ONCE, then stream iterations over dedicated channels with NO
per-tick task submission, RPC, or lease traffic at all.

``compile_loop(graph)`` installs a never-returning tick executor on each
stage actor (one ``__ray_call__`` submission per stage — the only task
the loop ever submits), wires the stages with credit-based streaming
channels (``RingChannel`` shm rings node-locally, ``TcpLoopServer``
across nodes), and returns a :class:`CompiledLoop`:

  * ``loop.put(x)`` enqueues an iteration input; it blocks only when the
    pipeline is ``credits`` iterations deep (backpressure propagates hop
    by hop through the ring credits — no control RPCs).
  * ``loop.get()`` returns the next iteration's output(s), in order,
    exactly once. ``put``/``get`` may run from different threads;
    ``run(x)`` is the synchronous convenience.
  * ``loop.teardown()`` closes the input ring; ``ChannelClosed`` cascades
    stage to stage exactly like the one-shot DAG — in-flight iterations
    drain first (close-after-drain STOP semantics).

Differences from the one-shot DAG worth knowing:

  * Channels DELIVER EVERY MESSAGE (bounded ring), not latest-wins — an
    iteration can never be overwritten by the next one.
  * Stage errors serialize through the pipe per iteration: the loop
    survives, the failing iteration's ``get()`` re-raises.
  * Stage workers are LEASE-PINNED: the raylet is told these workers
    park a resident loop, so the chaos orphan-lease watchdog never
    reclaims them as stranded grants (``PinLoopWorker``).
  * Observability: every stage counts ``ray_tpu_dag_loop_ticks_total``
    and gauges its output-channel occupancy; one ``dag.loop.tick`` span
    per ``dag_loop_span_every`` ticks rides the normal span flush.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import uuid
import weakref

from .channel import (ChannelClosed, RingChannel, TcpLoopReader,
                      TcpLoopServer)
from .compiled import _pack, _pack_error, _probe_node, _routable_host, _unpack
from .nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode


def _open_loop_reader(spec):
    """Open the reader end of an input spec ("ring", path, slots, readers,
    index) or ("tcp", address)."""
    if spec[0] == "tcp":
        return TcpLoopReader(spec[1])
    _, path, slot_size, n_slots, _n_readers, index = spec
    return RingChannel(path, slot_size, n_slots, reader_index=index)


def _create_loop_out_server(instance, n_slots: int, n_readers: int) -> str:
    """Phase-1 for a cross-node loop producer: create the streaming TCP
    server in the actor process and return its address."""
    server = TcpLoopServer(n_slots, n_readers, advertise=_routable_host())
    instance.__dict__["_dag_loop_out_server"] = server
    return server.address


_tick_metrics = None


def _loop_metrics():
    """Per-process loop metrics, created lazily so loop-free processes
    never start the metrics flusher."""
    global _tick_metrics
    if _tick_metrics is None:
        from ..util.metrics import Counter, Gauge

        from ..observability.loop_recorder import TICK_MS_BOUNDARIES
        from ..util.metrics import Histogram

        _tick_metrics = (
            Counter("ray_tpu_dag_loop_ticks_total",
                    "Iterations executed by resident compiled-loop stages",
                    tag_keys=("loop", "stage")),
            Gauge("ray_tpu_dag_loop_channel_occupancy",
                  "Unconsumed iterations queued in a loop stage's output "
                  "channel (0..credits; credits = backpressure engaged)",
                  tag_keys=("loop", "stage")),
            Histogram("ray_tpu_dag_loop_tick_ms",
                      "Per-tick stall attribution of resident loop stages: "
                      "time waiting on upstream input (bucket=wait_up), "
                      "computing (bucket=compute), and waiting on "
                      "downstream credits (bucket=wait_down)",
                      boundaries=TICK_MS_BOUNDARIES,
                      tag_keys=("loop", "stage", "bucket")),
        )
    return _tick_metrics


# Snapshot-file writes (snapshot aggregation + JSON + atomic replace,
# ~1ms on slow container filesystems) are time-gated: amortized over the
# span cadence alone they were the recorder's dominant cost on fast
# loops. The first flush always writes so stats() sees a young loop.
_STALL_FILE_MIN_S = 0.5


def _flush_stall(ring, hist, stall_tags, stall_path: str | None,
                 force: bool = False) -> None:
    """Drain the stage's stall ring into the aggregated histogram and
    (node-locally) an atomically-replaced snapshot file the driver's
    ``CompiledLoop.stats()`` reads without any actor RPC. Runs on the
    span cadence, never per tick; never raises into the loop."""
    if ring is None:
        return
    try:
        rows = ring.drain()
        if rows:
            # one bulk observe per bucket — per-sample observe() calls
            # (lock + tag-key resolution each) made the flush the
            # dominant recorder cost at ~45µs/tick amortized
            hist.observe_many([r[0] for r in rows], tags=stall_tags[0])
            hist.observe_many([r[1] for r in rows], tags=stall_tags[1])
            hist.observe_many([r[2] for r in rows], tags=stall_tags[2])
        now = time.monotonic()
        if stall_path and (force or now - ring.last_file_ts
                           >= _STALL_FILE_MIN_S):
            ring.last_file_ts = now
            tmp = stall_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(ring.snapshot(), f)
            os.replace(tmp, stall_path)
    except Exception:
        pass  # observability must never break the loop


def _loop_tick(instance, method_name: str, in_specs: list, out_desc,
               loop_id: str, span_every: int, stage_label: str | None = None,
               stall_path: str | None = None, stall_record: bool = True,
               stall_ring: int = 256) -> str:
    """The resident tick executor (ships to the stage actor via
    ``__ray_call__`` and never returns until teardown): read one
    iteration's inputs, apply the bound method, stream the result out.
    Blocking anywhere in the channel protocol IS the backpressure —
    which is exactly what the stall ring attributes: per tick, the time
    blocked in upstream ``read()`` (wait_up) vs the bound method
    (compute) vs downstream ``write()`` credit waits (wait_down), into a
    fixed-size in-process ring. Aggregates leave the process only on the
    ``span_every`` flush cadence (histogram + node-local snapshot file);
    the tick path itself does no allocation and no RPC for it."""
    from ..core.rpc import get_chaos

    readers = {i: _open_loop_reader(spec) for i, (kind, spec)
               in enumerate(in_specs) if kind == "chan"}
    if out_desc[0] == "tcp":
        out = instance.__dict__.pop("_dag_loop_out_server")
    else:
        _, path, slot_size, n_slots, n_readers = out_desc
        out = RingChannel(path, slot_size, n_slots)
        with open(path + ".ready", "w") as f:
            f.write("1")  # compile blocks on this marker (see _wait_ready)
    method = getattr(instance, method_name)
    stage = stage_label or method_name
    ticks = 0
    counter, occupancy, tick_hist = _loop_metrics()
    tags = {"loop": loop_id, "stage": stage}
    ring = None
    stall_tags = None
    if stall_record:
        from ..observability import loop_recorder

        ring = loop_recorder.get_stall_ring(loop_id, stage, stall_ring)
        stall_tags = tuple({"loop": loop_id, "stage": stage, "bucket": b}
                           for b in loop_recorder.STALL_BUCKETS)
    # Stall aggregates ride the span cadence; with tick spans disabled
    # they still flush, at the default stride.
    flush_every = span_every or 64
    perf = time.perf_counter
    try:
        while True:
            args, upstream_error = [], None
            r0 = perf()
            for i, (kind, spec) in enumerate(in_specs):
                if kind == "const":
                    args.append(spec)
                    continue
                value, is_error = _unpack(readers[i].read())
                if is_error and upstream_error is None:
                    upstream_error = value
                args.append(value)
            c0 = perf()
            if get_chaos().take_kill_loop_tick():
                # Deterministic chaos: this stage dies mid-loop, exactly
                # between consuming its inputs and producing its output.
                os._exit(1)
            if upstream_error is not None:
                out.write(_pack_error(upstream_error))
                ticks += 1
                continue
            t0 = time.time()
            try:
                result = method(*args)
                payload = _pack(result)  # inside try: unpicklable results
            except Exception as e:
                import traceback

                from ..core.status import RayTaskError

                payload = _pack_error(
                    RayTaskError(method_name, traceback.format_exc(), e))
            c1 = perf()
            out.write(payload)
            w1 = perf()
            ticks += 1
            if ring is not None:
                ring.record((c0 - r0) * 1e3, (c1 - c0) * 1e3,
                            (w1 - c1) * 1e3)
            counter.inc(tags=tags)
            occupancy.set(out.occupancy(), tags=tags)
            if ticks % flush_every == 0:
                _flush_stall(ring, tick_hist, stall_tags, stall_path)
            if span_every and ticks % span_every == 0:
                from ..observability import tracing

                tracing.record_span(tracing.make_span(
                    "dag.loop.tick", "dag", t0, time.time(), loop_id,
                    attrs={"stage": stage, "tick": ticks,
                           "out_occupancy": out.occupancy()}))
    except ChannelClosed:
        # final flush is forced past the file-write gate: teardown's
        # final_stats snapshot must see the complete tick history
        _flush_stall(ring, tick_hist, stall_tags, stall_path, force=True)
        out.close_writer()  # cascade teardown downstream
        return "closed"
    finally:
        for r in readers.values():
            r.close()
        out.close()


class CompiledLoop:
    """A compiled, resident iteration pipeline over stage actors.

    Build with :func:`compile_loop` (or
    ``node.experimental_compile_loop()``). One ``put`` produces exactly
    one ``get``-able output round; rounds stream in order with at most
    ``credits`` iterations in flight.
    """

    def __init__(self, output_node: DAGNode, max_buffer_size: int | None = None,
                 credits: int | None = None):
        from ..core import api as ray
        from ..core.config import get_config

        cfg = get_config()
        self.capacity = max_buffer_size or cfg.dag_channel_capacity
        self.credits = max(2, credits or cfg.dag_loop_credits)
        self._span_every = cfg.dag_loop_span_every
        self._stall_record = bool(
            getattr(cfg, "dag_loop_stall_recording", True))
        self._stall_ring = int(getattr(cfg, "dag_loop_stall_ring", 256))
        self._dir: str | None = None
        self._input_node: InputNode | None = None
        self._outputs: list[ClassMethodNode] = []
        self._loop_refs = []
        self._torn_down = False
        self._broken: str | None = None
        self._puts = 0
        self._gets = 0
        self._resume: list | None = None  # partial round after a get timeout
        from ..observability import tracing

        self.loop_id = tracing.new_trace_id()

        if isinstance(output_node, MultiOutputNode):
            self._outputs = list(output_node.outputs)
        else:
            self._outputs = [output_node]
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise TypeError("loop outputs must be actor method nodes")
        if len({id(o) for o in self._outputs}) != len(self._outputs):
            raise ValueError("a node may appear only once in a loop's "
                             "outputs (duplicates would alias ring cursors)")

        order = self._toposort()
        if self._input_node is None:
            raise ValueError("a compiled loop needs an InputNode")
        seen_actors: dict[bytes, str] = {}
        self._stage_nodes: list[ClassMethodNode] = []
        for node in order:
            if not isinstance(node, ClassMethodNode):
                continue
            if hasattr(node, "materialize_actor"):
                node.materialize_actor()
            actor_id = node.actor._actor_id
            if actor_id in seen_actors:
                raise ValueError(
                    f"actor used by both '{seen_actors[actor_id]}' and "
                    f"'{node.method_name}' — a compiled loop supports one "
                    "node per actor (create a separate actor per stage)")
            seen_actors[actor_id] = node.method_name
            self._stage_nodes.append(node)
        # Stable per-stage labels for metrics/stats: the method name,
        # disambiguated when two actors run same-named stages.
        self._stage_labels: list[str] = []
        name_counts: dict[str, int] = {}
        for node in self._stage_nodes:
            k = name_counts.get(node.method_name, 0)
            name_counts[node.method_name] = k + 1
            self._stage_labels.append(
                node.method_name if k == 0 else f"{node.method_name}#{k}")

        # Consumers per producer, in deterministic order; one reader end
        # per (consumer, arg position) so a node consuming the same
        # upstream twice gets two independent cursors. The driver is the
        # final consumer of every output node.
        consumers: dict[int, list] = {id(n): [] for n in order}
        for node in order:
            if isinstance(node, ClassMethodNode):
                for pos, arg in enumerate(node.args):
                    if isinstance(arg, DAGNode):
                        consumers[id(arg)].append((node, pos))
        for out in self._outputs:
            consumers[id(out)].append(("driver", 0))

        driver_node = ray.get_runtime_context().node_id
        node_of: dict[int, str] = {id(self._input_node): driver_node}
        for node in self._stage_nodes:
            node_of[id(node)] = ray.get(
                node.actor.__ray_call__.remote(_probe_node), timeout=60)

        self._dir = tempfile.mkdtemp(prefix="raytpu_dag_", dir="/dev/shm")
        # Producer -> writer descriptor + per-consumer reader specs.
        self._out_desc: dict[int, tuple] = {}
        self._reader_spec: dict[tuple, tuple] = {}  # (prod id, consumer idx)
        self._ring_paths: list[str] = []
        self._input_server = None
        for node in order:
            cons = consumers[id(node)]
            if not cons:
                continue
            n_readers = len(cons)
            # shm ring when every endpoint (producer + all consumers,
            # driver included) shares a node; streaming TCP otherwise.
            local = all(
                (driver_node if c[0] == "driver" else node_of[id(c[0])])
                == node_of[id(node)] for c in cons)
            if local:
                path = os.path.join(self._dir, f"lp_{uuid.uuid4().hex[:10]}")
                RingChannel(path, self.capacity, self.credits,
                            n_readers=n_readers, create=True).close()
                self._ring_paths.append(path)
                self._out_desc[id(node)] = (
                    "ring", path, self.capacity, self.credits, n_readers)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = (
                        "ring", path, self.capacity, self.credits,
                        n_readers, idx)
            elif node is self._input_node:
                self._input_server = TcpLoopServer(
                    self.credits, n_readers, advertise=_routable_host())
                self._out_desc[id(node)] = ("tcp", self._input_server.address)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = (
                        "tcp", self._input_server.address)
            else:
                addr = ray.get(node.actor.__ray_call__.remote(
                    _create_loop_out_server, self.credits, n_readers),
                    timeout=60)
                self._out_desc[id(node)] = ("tcp", addr)
                for idx in range(n_readers):
                    self._reader_spec[(id(node), idx)] = ("tcp", addr)

        # Driver ends: the input writer + one reader per output node.
        in_desc = self._out_desc[id(self._input_node)]
        if in_desc[0] == "tcp":
            self._input = self._input_server
        else:
            self._input = RingChannel(in_desc[1], self.capacity, self.credits)
        self._out_readers = []
        for node in self._outputs:
            idx = consumers[id(node)].index(("driver", 0))
            self._out_readers.append(
                _open_loop_reader(self._reader_spec[(id(node), idx)]))

        # Install the resident tick executors, upstream-last so consumers
        # are listening before producers can emit.
        self._actors = []
        self._actor_nodes: list[tuple[str, str]] = []  # (actor hex, node id)
        # Stage label -> node-local stall snapshot file (None for stages
        # on other nodes — those surface through the GCS metrics flush).
        self._stall_files: dict[str, str | None] = {}
        for i, node in enumerate(self._stage_nodes):
            self._actor_nodes.append(
                (node.actor._actor_id.hex(), node_of[id(node)]))
            in_specs = []
            for pos, arg in enumerate(node.args):
                if isinstance(arg, DAGNode):
                    idx = consumers[id(arg)].index((node, pos))
                    in_specs.append(
                        ("chan", self._reader_spec[(id(arg), idx)]))
                else:
                    in_specs.append(("const", arg))
            label = self._stage_labels[i]
            stall_path = (os.path.join(self._dir, f"stall_{i}.json")
                          if node_of[id(node)] == driver_node else None)
            self._stall_files[label] = stall_path
            ref = node.actor.__ray_call__.remote(
                _loop_tick, node.method_name, in_specs,
                self._out_desc[id(node)], self.loop_id, self._span_every,
                label, stall_path, self._stall_record, self._stall_ring)
            self._loop_refs.append(ref)
            self._actors.append(node.actor)
        self._wait_ready(timeout=cfg.dag_ready_timeout_s)
        # Lease-pin the stage workers: these actors now park a resident
        # loop task, and the orphan-lease watchdog must not mistake the
        # (idle-looking, never-returning) lease for a stranded grant.
        self._pinned = self._pin_workers(True)
        self.final_stats: dict | None = None  # captured at teardown
        _register_loop(self)

    # ------------------------------------------------------------- plumbing
    def _toposort(self) -> list[DAGNode]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(node: DAGNode) -> None:
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, InputNode):
                if self._input_node is not None and self._input_node is not node:
                    raise ValueError("a compiled loop supports one InputNode")
                self._input_node = node
                order.append(node)
                return
            if isinstance(node, ClassMethodNode):
                if not node.upstream():
                    raise ValueError(
                        f"{node.method_name}.bind(...) has no upstream node — "
                        "a loop stage needs at least one DAG input")
                for up in node.upstream():
                    visit(up)
                order.append(node)
                return
            raise TypeError(f"unsupported DAG node {type(node).__name__}")

        for out in self._outputs:
            visit(out)
        return order

    def _wait_ready(self, timeout: float) -> None:
        from ..core import api as ray

        markers = [desc[1] + ".ready"
                   for nid, desc in self._out_desc.items()
                   if desc[0] == "ring" and nid != id(self._input_node)]
        deadline = time.monotonic() + timeout
        while True:
            if all(os.path.exists(m) for m in markers):
                return
            done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
            if done:
                ray.get(done[0])
                raise RuntimeError("loop executor exited during compile")
            if time.monotonic() > deadline:
                missing = [m for m in markers if not os.path.exists(m)]
                raise TimeoutError(
                    f"{len(missing)} loop executor(s) not ready after "
                    f"{timeout}s: {missing[:3]}")
            time.sleep(0.01)

    def _pin_workers(self, pinned: bool) -> bool:
        try:
            from ..core.worker import global_worker

            w = global_worker()
            for actor_hex, node_id in self._actor_nodes:
                w.pin_loop_worker(actor_hex, pinned, node_id=node_id)
            return pinned
        except Exception:
            return False  # pinning is protective, never fatal

    def _check_stage_death(self) -> None:
        """A completed loop ref at steady state means its stage DIED (or
        its install failed): surface the real error and break the loop."""
        from ..core import api as ray

        done, _ = ray.wait(list(self._loop_refs), num_returns=1, timeout=0)
        if not done:
            return
        try:
            result = ray.get(done[0])
            if result == "closed":
                return  # normal cascade exit, not a death
            err: Exception = ChannelClosed(f"loop stage exited: {result!r}")
        except Exception as e:
            err = e
        self._break(f"stage died: {err}")
        raise err

    def _break(self, reason: str) -> None:
        """Force-teardown after a failure: unblock every parked stage by
        force-closing the shm rings (a dead stage's consumers would
        otherwise spin forever on a channel nobody will ever close)."""
        if self._broken is not None:
            return
        self._broken = reason
        if self._input is not None:
            self._input.force_close()
        for path in self._ring_paths:
            try:
                RingChannel(path, self.capacity, self.credits).force_close()
            except OSError:
                pass
        self._pin_workers(False)

    # ------------------------------------------------------------------- API
    @property
    def in_flight(self) -> int:
        """Iterations put but not yet fully consumed by ``get``."""
        return self._puts - self._gets

    def stats(self, fallback_gcs: bool = True) -> dict:
        """Observability snapshot of the resident pipeline: per-stage
        tick stall attribution plus put/get progress and a bottleneck
        classification. Reads the node-local snapshot files the stages
        flush on the span cadence — no actor RPC (a resident stage's
        actor is parked in ``_loop_tick`` and could never answer one).
        Stages on OTHER nodes have no local file; their aggregates are
        rebuilt from the GCS-flushed histogram when ``fallback_gcs``.
        Stage ``state`` is ``compute_bound`` / ``starved`` (wait_up
        dominant) / ``backpressured`` (wait_down dominant) / ``idle``;
        the loop's ``bottleneck`` is the stage with the highest compute
        share — everyone else is waiting on it."""
        from ..observability import loop_recorder

        stages: dict[str, dict] = {}
        unseen = []
        for label, path in self._stall_files.items():
            snap = None
            if path:
                try:
                    with open(path) as f:
                        snap = json.load(f)
                except Exception:
                    snap = None
            if snap is None:
                unseen.append(label)
                snap = {"ticks": 0, "overflowed": False, "totals_ms": {},
                        "frac": {}, "recent_mean_ms": {}}
            stages[label] = snap
        if unseen and fallback_gcs and self._stall_record:
            for label, snap in _stall_from_metrics(self.loop_id).items():
                if not stages.get(label, {}).get("ticks"):
                    stages[label] = snap
        for snap in stages.values():
            snap["state"] = loop_recorder.classify_stage(
                snap.get("frac"), snap.get("ticks", 0))
        return {
            "loop_id": self.loop_id,
            "stages": stages,
            "bottleneck": loop_recorder.classify_loop(stages),
            "recording": self._stall_record,
            "puts": self._puts,
            "gets": self._gets,
            "in_flight": self.in_flight,
            "credits": self.credits,
            "broken": self._broken,
            "torn_down": self._torn_down,
        }

    def put(self, value, timeout: float | None = 60.0) -> None:
        """Enqueue one iteration input. Blocks only when the pipeline
        already holds ``credits`` unconsumed iterations (backpressure)."""
        if self._torn_down or self._broken:
            raise ChannelClosed(self._broken or "loop torn down")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                self._input.write(_pack(value), timeout=0.25)
                self._puts += 1
                return
            except TimeoutError:
                self._check_stage_death()
                if deadline is not None and time.monotonic() > deadline:
                    raise

    def get(self, timeout: float | None = 60.0):
        """Next iteration's output (tuple for MultiOutputNode), in put
        order. Re-raises a stage's per-iteration error; the loop itself
        survives errors and keeps streaming."""
        if self._torn_down:
            raise ChannelClosed("loop torn down")
        if self._broken and self._resume is None:
            raise ChannelClosed(self._broken)
        deadline = None if timeout is None else time.monotonic() + timeout
        # Resume a round a previous timed-out get left half-read, so
        # output cursors never desync across rounds.
        results = self._resume if self._resume is not None else []
        self._resume = None
        first_error = None
        while len(results) < len(self._out_readers):
            reader = self._out_readers[len(results)]
            try:
                payload = reader.read(timeout=0.25)
            except TimeoutError:
                # Slicing the wait keeps stage-death detection prompt; a
                # transient timeout here is NOT a failed round yet.
                self._check_stage_death()
                if deadline is not None and time.monotonic() > deadline:
                    # Preserve the half-read round so the next get()
                    # resumes at the SAME reader — cursors never desync.
                    self._resume = results
                    raise TimeoutError(
                        f"loop output idle past {timeout}s "
                        f"({self.in_flight} iterations in flight)")
                continue
            except ChannelClosed:
                self._break("loop output channel closed")
                raise
            results.append(_unpack(payload))
        self._gets += 1
        values = []
        for value, is_error in results:
            if is_error and first_error is None:
                first_error = value
            values.append(value)
        if first_error is not None:
            from ..core.status import RayTaskError

            raise (first_error.as_instanceof_cause()
                   if isinstance(first_error, RayTaskError) else first_error)
        return values[0] if len(values) == 1 else tuple(values)

    def run(self, value, timeout: float | None = 60.0):
        """Synchronous convenience: one put + one get."""
        self.put(value, timeout=timeout)
        return self.get(timeout=timeout)

    # --------------------------------------------------------------- teardown
    def teardown(self, timeout: float = 30.0) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        from ..chaos import clock as chaos_clock
        from ..core import api as ray

        t0 = chaos_clock.now()
        input_ch = getattr(self, "_input", None)
        if input_ch is not None and self._broken is None:
            input_ch.close_writer(timeout=min(timeout, 5.0))
        try:
            ray.get(list(self._loop_refs), timeout=timeout)
        except Exception:
            # A stage died or is stuck on a dead peer's channel: force
            # the cascade through every ring so the rest exit.
            self._break("teardown")
            try:
                ray.get(list(self._loop_refs), timeout=timeout)
            except Exception:
                pass
        self._pin_workers(False)
        if input_ch is not None:
            input_ch.close()
        for r in getattr(self, "_out_readers", []):
            r.close()
        if self._dir is not None:
            try:
                # Last look at the stall files before they vanish — the
                # train runner reports this as its loop_stats.
                self.final_stats = self.stats(fallback_gcs=False)
            except Exception:
                pass
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)
        self.torn_down_in_s = chaos_clock.now() - t0

    def __del__(self):
        try:
            self.teardown(timeout=1.0)
        except Exception:
            pass


# Driver-local registry of live loops: CompiledLoop objects only exist
# in the process that compiled them, so `state.loop_stats()` / the
# dashboard's /api/loops answer from here (weak — teardown or GC drops
# the entry without bookkeeping).
_live_loops: "weakref.WeakValueDictionary[str, CompiledLoop]" = \
    weakref.WeakValueDictionary()


def _register_loop(loop: CompiledLoop) -> None:
    _live_loops[loop.loop_id] = loop


def live_loop_stats() -> list[dict]:
    """``stats()`` for every live (not torn down) compiled loop this
    driver process owns, newest first by loop id order of creation."""
    out = []
    for loop in list(_live_loops.values()):
        if loop._torn_down:
            continue
        try:
            out.append(loop.stats())
        except Exception:
            continue
    return out


def _stall_from_metrics(loop_id: str) -> dict[str, dict]:
    """Cross-node fallback for ``CompiledLoop.stats()``: rebuild a
    stage's stall aggregates from the GCS-aggregated
    ``ray_tpu_dag_loop_tick_ms`` histogram rows (remote stages flush it
    through the ordinary metrics flusher; there is no node-local file to
    read). Best-effort — returns {} without a cluster."""
    from ..observability.loop_recorder import STALL_BUCKETS

    try:
        from ..util.metrics import get_metrics

        rows = get_metrics()
    except Exception:
        return {}
    stages: dict[str, dict] = {}
    for m in rows:
        if m.get("name") != "ray_tpu_dag_loop_tick_ms":
            continue
        tags = m.get("tags") or {}
        if tags.get("loop") != loop_id:
            continue
        st = stages.setdefault(tags.get("stage", "?"), {
            "ticks": 0, "overflowed": False,
            "totals_ms": {b: 0.0 for b in STALL_BUCKETS},
            "frac": {}, "recent_mean_ms": {}})
        bucket = tags.get("bucket", "")
        if bucket in st["totals_ms"]:
            st["totals_ms"][bucket] += float(m.get("value") or 0.0)
            if bucket == "compute":
                st["ticks"] += int(m.get("count") or 0)
    for st in stages.values():
        total = sum(st["totals_ms"].values()) or 1.0
        st["frac"] = {b: round(v / total, 4)
                      for b, v in st["totals_ms"].items()}
    return stages


def compile_loop(output_node: DAGNode, max_buffer_size: int | None = None,
                 credits: int | None = None) -> CompiledLoop:
    """Compile a DAG built with ``actor.method.bind(...)`` into a
    persistent streaming loop (see module docstring)."""
    return CompiledLoop(output_node, max_buffer_size=max_buffer_size,
                        credits=credits)
