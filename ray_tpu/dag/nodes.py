"""DAG node types (reference ``python/ray/dag/dag_node.py`` family)."""

from __future__ import annotations

from typing import Any


class DAGNode:
    def experimental_compile(self, max_buffer_size: int = 1 << 20):
        from .compiled import CompiledDAG

        return CompiledDAG(self, max_buffer_size=max_buffer_size)

    def experimental_compile_loop(self, max_buffer_size: int | None = None,
                                  credits: int | None = None):
        """Compile into a persistent streaming loop (``dag/loop.py``):
        resident tick executors + credit-based streaming channels, for
        steady-state iteration (``put``/``get``) instead of one-shot
        ``execute``."""
        from .loop import CompiledLoop

        return CompiledLoop(self, max_buffer_size=max_buffer_size,
                            credits=credits)


class InputNode(DAGNode):
    """The driver-supplied input (``with InputNode() as inp:``)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass


class ClassMethodNode(DAGNode):
    def __init__(self, actor, method_name: str, args: tuple, kwargs: dict):
        if kwargs:
            raise ValueError("compiled DAGs support positional args only")
        self.actor = actor
        self.method_name = method_name
        self.args = args

    def upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: list[DAGNode]):
        self.outputs = list(outputs)


class _DagReducer:
    """Hidden reducer actor backing an AllReduceNode."""

    _OPS = {
        "sum": lambda vs: _reduce_add(vs),
        "mean": lambda vs: _reduce_add(vs) / len(vs),
        "max": lambda vs: max(vs),
        "min": lambda vs: min(vs),
    }

    def __init__(self, op):
        self._fn = op if callable(op) else self._OPS[op]

    def reduce(self, *values):
        return self._fn(list(values))


def _reduce_add(values):
    import functools
    import operator

    return functools.reduce(operator.add, values)


class AllReduceNode(ClassMethodNode):
    """Collective node: reduces N upstream nodes' outputs into one value
    (reference ``python/ray/dag/collective_node.py``). The TPU design
    keeps TENSOR collectives inside compiled XLA programs (SURVEY §2.5);
    this is the host-side DAG collective for cross-actor results — it
    compiles to a hidden reducer actor wired into the channel graph like
    any other stage."""

    def __init__(self, nodes: list, op: str | Any = "sum"):
        if len(nodes) < 2:
            raise ValueError("allreduce needs at least two upstream nodes")
        if not callable(op) and op not in _DagReducer._OPS:
            raise ValueError(f"unknown allreduce op {op!r}")
        self.actor = None  # materialized at compile time
        self.method_name = "reduce"
        self.args = tuple(nodes)
        self._op = op

    def materialize_actor(self) -> None:
        if self.actor is None:
            from ..core import api as ray

            self.actor = ray.remote(_DagReducer).options(num_cpus=0.1).remote(self._op)
            self._owned_actor = True


class _Collective:
    """``collective.allreduce.bind([n1, n2], op=...)`` compat surface."""

    class _AllReduce:
        @staticmethod
        def bind(nodes: list, op: str | Any = "sum") -> AllReduceNode:
            return AllReduceNode(nodes, op)

    allreduce = _AllReduce()


collective = _Collective()
