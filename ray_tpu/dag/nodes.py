"""DAG node types (reference ``python/ray/dag/dag_node.py`` family)."""

from __future__ import annotations

from typing import Any


class DAGNode:
    def experimental_compile(self, max_buffer_size: int = 1 << 20):
        from .compiled import CompiledDAG

        return CompiledDAG(self, max_buffer_size=max_buffer_size)


class InputNode(DAGNode):
    """The driver-supplied input (``with InputNode() as inp:``)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass


class ClassMethodNode(DAGNode):
    def __init__(self, actor, method_name: str, args: tuple, kwargs: dict):
        if kwargs:
            raise ValueError("compiled DAGs support positional args only")
        self.actor = actor
        self.method_name = method_name
        self.args = args

    def upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: list[DAGNode]):
        self.outputs = list(outputs)
