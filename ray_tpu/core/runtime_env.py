"""Runtime-env application shared by the raylet worker pool and the job
manager (reference ``python/ray/_private/runtime_env/``): env_vars merge
(``None`` unsets) and working_dir with PYTHONPATH threading so spawned
processes can still import ray_tpu from its source tree.
"""

from __future__ import annotations

import os


def package_root() -> str:
    """Directory containing the ``ray_tpu`` package (the repo root)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def apply_runtime_env(env: dict, runtime_env: dict | None) -> str | None:
    """Mutate ``env`` per ``runtime_env``; returns the working_dir to use
    as the subprocess cwd (or None). Does not validate the directory —
    callers decide whether a missing dir warns or fails."""
    renv = runtime_env or {}
    for key, value in (renv.get("env_vars") or {}).items():
        if value is None:
            env.pop(key, None)
        else:
            env[key] = str(value)
    working_dir = renv.get("working_dir") or None
    if working_dir is not None:
        paths = [working_dir, package_root()]
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
    return working_dir
