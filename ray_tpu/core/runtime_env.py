"""Runtime-env application shared by the raylet worker pool and the job
manager (reference ``python/ray/_private/runtime_env/``): env_vars merge
(``None`` unsets), working_dir with PYTHONPATH threading, and the
dependency plugins — ``py_modules`` (staged local packages) and ``pip``
(requirements installed into a content-addressed target dir) — backed by
a URI cache (reference ``uri_cache.py``): each unique spec is prepared
ONCE under ``/tmp/ray_tpu/runtime_env/<plugin>/<hash>`` with a sentinel
lock, reused by every worker, and LRU-evicted over a size cap.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import subprocess
import sys
import time

logger = logging.getLogger(__name__)

URI_CACHE_ROOT = os.environ.get("RAY_TPU_RUNTIME_ENV_CACHE",
                                "/tmp/ray_tpu/runtime_env")
URI_CACHE_MAX_BYTES = int(os.environ.get("RAY_TPU_RUNTIME_ENV_CACHE_BYTES",
                                         str(2 << 30)))


def package_root() -> str:
    """Directory containing the ``ray_tpu`` package (the repo root)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------------- URI cache
def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _evict_lru(plugin_root: str, incoming_hint: int = 0) -> None:
    """Drop least-recently-used cache entries once the plugin's cache
    exceeds the cap (reference uri_cache.py eviction)."""
    try:
        entries = [os.path.join(plugin_root, d) for d in os.listdir(plugin_root)]
    except OSError:
        return
    sized = [(p, _dir_bytes(p), os.path.getmtime(p)) for p in entries if os.path.isdir(p)]
    total = sum(s for _, s, _ in sized) + incoming_hint
    if total <= URI_CACHE_MAX_BYTES:
        return
    for path, size, _mtime in sorted(sized, key=lambda e: e[2]):
        if total <= URI_CACHE_MAX_BYTES:
            break
        shutil.rmtree(path, ignore_errors=True)
        total -= size
        logger.info("runtime_env cache evicted %s (%.1f MB)", path, size / 1e6)


def _prepare_cached(plugin: str, uri_hash: str, build) -> str:
    """Create-once semantics: the first caller builds into a tmp dir and
    renames it in; concurrent callers wait on the ready marker."""
    plugin_root = os.path.join(URI_CACHE_ROOT, plugin)
    os.makedirs(plugin_root, exist_ok=True)
    target = os.path.join(plugin_root, uri_hash)
    if os.path.isdir(target):
        os.utime(target)  # LRU touch
        return target
    tmp = f"{target}.building.{os.getpid()}"
    try:
        os.makedirs(tmp)
    except FileExistsError:
        pass
    else:
        try:
            _evict_lru(plugin_root)
            build(tmp)
            try:
                os.rename(tmp, target)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    deadline = time.monotonic() + 300.0
    while not os.path.isdir(target):
        if time.monotonic() > deadline:
            raise TimeoutError(f"runtime_env {plugin}:{uri_hash} never became ready")
        time.sleep(0.1)
    return target


def _hash_paths(paths: list[str]) -> str:
    """Content hash over module trees so edits produce a fresh URI."""
    h = hashlib.sha1()
    for p in sorted(paths):
        p = os.path.abspath(p)
        h.update(p.encode())
        if os.path.isfile(p):
            h.update(open(p, "rb").read())
            continue
        for root, dirs, files in os.walk(p):
            dirs.sort()
            for f in sorted(files):
                if f.endswith(".pyc"):
                    continue
                fp = os.path.join(root, f)
                h.update(os.path.relpath(fp, p).encode())
                try:
                    h.update(open(fp, "rb").read())
                except OSError:
                    pass
    return h.hexdigest()[:16]


def ensure_py_modules(modules: list[str]) -> str:
    """Stage local module dirs/files into one cached PYTHONPATH entry
    (reference py_modules.py, minus the remote-URI download — single-host
    path semantics, matching working_dir)."""

    def build(tmp: str) -> None:
        for m in modules:
            m = os.path.abspath(m)
            dest = os.path.join(tmp, os.path.basename(m.rstrip("/")))
            if os.path.isdir(m):
                shutil.copytree(m, dest, ignore=shutil.ignore_patterns("__pycache__"))
            else:
                shutil.copy2(m, dest)

    return _prepare_cached("py_modules", _hash_paths(modules), build)


def ensure_pip(requirements: list[str] | dict) -> str:
    """Install requirements ONCE into a cached ``--target`` dir
    (reference pip.py + uri_cache.py). ``--no-build-isolation`` so local
    source packages build offline with the baked setuptools (this
    environment has zero egress; remote packages need a reachable index)."""
    if isinstance(requirements, dict):
        requirements = requirements.get("packages", [])
    reqs = [str(r) for r in requirements]
    uri = hashlib.sha1("\n".join(sorted(reqs)).encode()).hexdigest()[:16]

    def build(tmp: str) -> None:
        cmd = [sys.executable, "-m", "pip", "install", "--quiet",
               "--no-build-isolation", "--target", tmp, *reqs]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip runtime_env install failed ({' '.join(reqs)}):\n"
                f"{proc.stderr[-2000:]}")

    return _prepare_cached("pip", uri, build)


def apply_runtime_env(env: dict, runtime_env: dict | None) -> str | None:
    """Mutate ``env`` per ``runtime_env``; returns the working_dir to use
    as the subprocess cwd (or None). Does not validate the directory —
    callers decide whether a missing dir warns or fails."""
    renv = runtime_env or {}
    for key, value in (renv.get("env_vars") or {}).items():
        if value is None:
            env.pop(key, None)
        else:
            env[key] = str(value)
    extra_paths: list[str] = []
    working_dir = renv.get("working_dir") or None
    if working_dir is not None:
        extra_paths.append(working_dir)
    if renv.get("py_modules"):
        extra_paths.append(ensure_py_modules(list(renv["py_modules"])))
    pip_spec = renv.get("pip") or renv.get("uv")  # uv: same offline semantics
    if pip_spec:
        extra_paths.append(ensure_pip(pip_spec))
    if extra_paths:
        paths = [*extra_paths, package_root()]
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
    return working_dir


# ------------------------------------------------- interpreter-level plugins


class RuntimeEnvSetupError(RuntimeError):
    """A runtime_env plugin could not be satisfied on this node
    (reference ``_private/runtime_env``'s setup failure surface)."""


def _conda_base() -> str | None:
    import shutil

    exe = os.environ.get("CONDA_EXE") or shutil.which("conda") \
        or shutil.which("micromamba") or shutil.which("mamba")
    if exe is None:
        return None
    try:
        out = subprocess.run([exe, "info", "--base"], capture_output=True,
                             text=True, timeout=30)
        base = out.stdout.strip().splitlines()[-1].strip() if out.returncode == 0 else ""
    except Exception:
        base = ""
    if not base:
        # micromamba: root prefix env var
        base = os.environ.get("MAMBA_ROOT_PREFIX", "")
    return base or None


def _conda_env_python(spec) -> str:
    """Python interpreter of the requested conda env (reference
    ``runtime_env/conda.py``): a string names an EXISTING env; a dict is
    an environment.yml-style spec created once and cached by hash."""
    base = _conda_base()
    if base is None:
        raise RuntimeEnvSetupError(
            "runtime_env 'conda' requires a conda/micromamba installation "
            "on the node; none found on PATH (and CONDA_EXE unset)")
    if isinstance(spec, str):
        candidates = [os.path.join(base, "envs", spec, "bin", "python")]
        if spec in ("base", ""):
            candidates.insert(0, os.path.join(base, "bin", "python"))
        for c in candidates:
            if os.path.exists(c):
                return c
        raise RuntimeEnvSetupError(
            f"conda env {spec!r} not found under {base}/envs")
    # dict spec: create under the URI cache, keyed by content hash
    import hashlib
    import json
    import shutil as _shutil

    blob = json.dumps(spec, sort_keys=True).encode()
    uri = hashlib.sha1(blob).hexdigest()[:16]

    def build(target: str) -> None:
        yml = os.path.join(target, "environment.yml")
        os.makedirs(target, exist_ok=True)
        with open(yml, "w") as f:
            json.dump(spec, f)
        exe = os.environ.get("CONDA_EXE") or _shutil.which("conda") \
            or _shutil.which("micromamba")
        r = subprocess.run(
            [exe, "env", "create", "--prefix", os.path.join(target, "env"),
             "--file", yml, "--yes"],
            capture_output=True, text=True, timeout=1800)
        if r.returncode != 0:
            raise RuntimeEnvSetupError(
                f"conda env create failed:\n{r.stderr[-2000:]}")

    target = _prepare_cached("conda", uri, build)
    return os.path.join(target, "env", "bin", "python")


def resolve_python_executable(runtime_env: dict | None) -> str | None:
    """Interpreter override for worker processes: ``py_executable``
    (reference ``runtime_env/py_executable.py``) or ``conda`` (reference
    ``runtime_env/conda.py`` — hermetic env, its python). None = the
    raylet's own interpreter."""
    renv = runtime_env or {}
    if renv.get("py_executable"):
        py = renv["py_executable"]
        if not os.path.exists(py):
            raise RuntimeEnvSetupError(f"py_executable {py!r} does not exist")
        return py
    if renv.get("conda"):
        return _conda_env_python(renv["conda"])
    return None


def wrap_worker_command(cmd: list[str], runtime_env: dict | None) -> list[str]:
    """``container``/``image_uri`` plugin (reference
    ``runtime_env/image_uri.py``): run the worker inside a container via
    podman/docker when a runtime exists — host network (the worker must
    reach the raylet/GCS sockets) and /tmp + the repo mounted so the shm
    store arena and source tree resolve. Raises a clear setup error when
    no container runtime is installed."""
    import shutil

    renv = runtime_env or {}
    spec = renv.get("container") or (
        {"image": renv["image_uri"]} if renv.get("image_uri") else None)
    if not spec:
        return cmd
    image = spec.get("image") if isinstance(spec, dict) else spec
    engine = shutil.which("podman") or shutil.which("docker")
    if engine is None:
        raise RuntimeEnvSetupError(
            "runtime_env 'container'/'image_uri' requires podman or docker "
            "on the node; neither found on PATH")
    run_opts = list(spec.get("run_options") or []) if isinstance(spec, dict) else []
    repo = package_root()
    return [engine, "run", "--rm", "--network=host",
            "-v", "/tmp:/tmp", "-v", "/dev/shm:/dev/shm",
            "-v", f"{repo}:{repo}",
            *run_opts, image, *cmd]
