"""Raylet: the per-node agent.

Equivalent of the reference's ``src/ray/raylet/``: ``NodeManager``
(``node_manager.h:118``) + ``WorkerPool`` (``worker_pool.h:524``) +
``LocalTaskManager``/``ClusterTaskManager`` (``scheduling/``) + the local
object store (our native shm store standing in for the in-raylet plasma
runner) + ``LocalObjectManager`` duties (object transfer; spill is
delegated to eviction in round 1).

Protocol surface (RPC methods):
  RequestWorkerLease / ReturnWorker      — worker lease protocol
                                           (node_manager.cc:1910)
  RegisterWorker                         — worker startup handshake
  PlasmaCreate/Seal/GetInfo/Contains/
  AddRef/Release/Delete/Wait             — object store service
  FetchObjectChunk                       — chunked object transfer between
                                           nodes (object_manager.h:117)
  ReserveBundle/CommitBundle/
  CancelBundle/ReturnBundle              — placement-group 2PC
  HealthCheck                            — GCS health pings
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from .config import get_config
from .ids import NodeID, WorkerID
from .resources import NodeResources, ResourceSet
from .rpc import RetryableRpcClient, RpcClient, RpcServer, get_chaos, spawn
from ..chaos import clock as chaos_clock
from ..native.store import ShmStore, StoreFullError

logger = logging.getLogger(__name__)


class ObjectMissingOnHolder(Exception):
    """A node listed as holding an object reported it absent (evicted)."""


class PidHandle:
    """Popen-compatible handle for a worker forked by the zygote (not our
    child, so ``waitpid`` is unavailable; the zygote auto-reaps). Exposes
    the subset of the Popen surface the raylet uses: poll/wait/terminate/
    kill/pid/returncode. Identity is (pid, /proc start time) so a recycled
    pid is never mistaken for the live worker (or SIGKILLed at teardown)."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None
        self._starttime = self._read_starttime(pid)

    @staticmethod
    def _read_starttime(pid: int) -> str | None:
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().rsplit(")", 1)[-1].split()[19]  # field 22
        except (OSError, IndexError):
            return None

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        current = self._read_starttime(self.pid)
        if current is None or (self._starttime is not None
                               and current != self._starttime):
            self.returncode = -1  # gone, or the pid was recycled
            return self.returncode
        return None

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(f"worker pid {self.pid}", timeout)
            time.sleep(0.02)
        return self.returncode

    def _signal(self, sig) -> None:
        if self.poll() is not None:
            return  # dead or recycled pid: never signal a stranger
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self) -> None:
        import signal

        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        import signal

        self._signal(signal.SIGKILL)


@dataclass
class ZygoteHandle:
    """One runtime-env-keyed forkserver (worker_zygote.py): the process,
    its boot state, and the lock serializing fork-protocol framing."""

    renv: dict | None = None
    proc: subprocess.Popen | None = None
    booting: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)


# Spawn-latency evidence for the zygote pool (mode "pooled" = forked from
# a warm zygote image; "cold" = direct Popen paying interpreter boot +
# imports). Module-level: many in-process raylets (the Cluster harness)
# share one registry entry instead of each registering a duplicate.
_SPAWN_HIST: "object | None" = None


def _spawn_hist():
    global _SPAWN_HIST
    if _SPAWN_HIST is None:
        from ..util.metrics import Histogram

        _SPAWN_HIST = Histogram(
            "ray_tpu_worker_spawn_ms",
            "Worker spawn-to-register latency by spawn mode "
            "(cold Popen vs zygote-pool fork)",
            tag_keys=("mode",))
    return _SPAWN_HIST


@dataclass
class WorkerHandle:
    worker_id: str
    address: str = ""
    pid: int = 0
    proc: subprocess.Popen | None = None
    state: str = "starting"  # starting | idle | leased | dedicated | dead
    actor_id: str = ""
    # How this process came to be: "pooled" = forked from a warm zygote
    # image (~ms), "cold" = direct Popen (interpreter boot + imports).
    spawn_mode: str = "cold"
    # monotonic stamp at spawn, cleared once the register latency has
    # been observed into ray_tpu_worker_spawn_ms.
    spawn_started_at: float = 0.0
    # Hash of the runtime env this worker was started with ("" = default);
    # leases only match workers with the same env (worker_pool.h:524
    # runtime-env-hash matching).
    env_hash: str = ""
    lease_resources: ResourceSet = field(default_factory=ResourceSet)
    # Bundle this lease draws from, if the task runs in a placement group.
    bundle_key: tuple | None = None
    registered: asyncio.Future | None = None
    last_idle_time: float = 0.0
    # When the current lease was granted + whether its task is retriable —
    # the memory monitor's OOM policy kills the newest retriable lease
    # (reference worker_killing_policy.cc retriable-LIFO).
    lease_time: float = 0.0
    retriable: bool = False
    # Lease-grant acknowledgement: the owner acks right after it receives
    # the grant reply. A lease still un-acked past lease_orphan_timeout_s
    # means the reply was lost (the owner will retry elsewhere) and the
    # reservation would strand forever — the watchdog reclaims it.
    # Granted-at runs on the chaos clock so virtual time replays it.
    lease_acked: bool = True
    lease_granted_at: float = 0.0
    # pushes_total sampled at the watchdog's first orphan probe (a second
    # unchanged sample confirms the owner really never used the lease).
    orphan_probe: int | None = None
    # Worker parks a resident compiled-loop executor (dag/loop.py): the
    # owner declared it via PinLoopWorker. A parked loop is indistinguishable
    # from a stranded grant to the orphan watchdog (no pushes, never
    # finishes, probe may be unreachable under chaos) — pinned leases are
    # exempt from orphan reclaim until the owner unpins at loop teardown.
    loop_pinned: bool = False


class Raylet:
    def __init__(
        self,
        gcs_address: str,
        host: str = "127.0.0.1",
        port: int = 0,
        num_cpus: float | None = None,
        resources: dict | None = None,
        labels: dict | None = None,
        object_store_capacity: int | None = None,
        session_dir: str = "/tmp/ray_tpu",
    ):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self._server = RpcServer(host, port, tag="raylet")
        self._server.register_service(self)
        self._gcs = RetryableRpcClient(gcs_address)

        cfg = get_config()
        total: dict = dict(resources or {})
        total.setdefault("CPU", num_cpus if num_cpus is not None else (os.cpu_count() or 1))
        from ..tpu import detect_tpu_resources

        for k, v in detect_tpu_resources().items():
            total.setdefault(k, v)
        if object_store_capacity is None:
            object_store_capacity = cfg.object_store_minimum_memory_bytes
        total.setdefault("object_store_memory", float(object_store_capacity))
        self.resources = NodeResources(total, labels)

        os.makedirs(session_dir, exist_ok=True)
        self.store_path = os.path.join(
            "/dev/shm", f"raytpu_store_{self.node_id.hex()[:12]}"
        )
        self.store = ShmStore(self.store_path, object_store_capacity)
        self.object_store_capacity = object_store_capacity

        self._workers: dict[str, WorkerHandle] = {}
        self._idle: list[str] = []
        self._lease_waiters: list[asyncio.Future] = []
        # Resource-admission queue: (priority, seq)-ordered waiters; the
        # releaser hands reservations to the head directly, so a flood of
        # new task leases can never starve a parked actor creation
        # (fixes the scheduler-fairness starvation; reference:
        # cluster_task_manager.cc queue ordering).
        self._admission_queue: list[dict] = []
        self._admission_seq = 0
        self._pg_bundles: dict[tuple[str, int], dict] = {}  # (pg_id, idx) -> {resources, committed}
        self._tasks: list[asyncio.Task] = []
        self._node_table: dict[str, dict] = {}
        # Node-table refresh sharing: concurrent refreshers ride ONE
        # in-flight GetAllNodes, and bounded-staleness callers (the
        # infeasible-lease wait loop) accept a recent cache outright.
        self._node_table_ts = 0.0
        self._node_table_refresh: asyncio.Future | None = None
        # Lease admission fast-path: resource shapes recur (a 100k-task
        # bench is 100k×{"CPU": 1}) — cache the fixed-point ResourceSet
        # per shape instead of rebuilding it for every request.
        self._request_shape_cache: dict[tuple, ResourceSet] = {}
        self._remote_store_clients: dict[str, RpcClient] = {}
        self._fetching: dict[bytes, asyncio.Future] = {}
        self._session_dir = session_dir
        self._shutdown = False
        # object_id -> {size, state} for the state API (ListObjects)
        self._object_meta: dict[bytes, dict] = {}

        # --- spill manager (LocalObjectManager, local_object_manager.h:110):
        # primary copies are pinned in the store; under memory pressure the
        # oldest unreferenced pinned objects are written to disk and deleted
        # from shm, then restored on the next Get/Fetch.
        self._spill_dir = os.path.join(session_dir, f"spill-{self.node_id.hex()[:12]}")
        self._spilled: dict[bytes, tuple[int, int]] = {}  # oid -> (data_size, meta_size)
        self._spill_pending: dict[bytes, bytes] = {}  # disk write still in flight
        self._pinned: dict[bytes, int] = {}  # oid -> total size, insertion-ordered
        self._last_oom_kill = 0.0
        self._spilled_bytes_total = 0
        self._restored_bytes_total = 0
        # Memory observability: spill/restore object counts, creations that
        # only succeeded after a synchronous spill (the reference's
        # "fallback allocation" analogue), and the high-water store mark.
        self._spilled_objects_total = 0
        self._restored_objects_total = 0
        self._fallback_allocations_total = 0
        self._store_used_peak = 0
        # Overridable for tests: returns fraction of node memory in use.
        self._memory_usage_fn = _node_memory_usage_fraction
        # Outstanding pin_read store refs per reader (worker_id), released
        # in bulk if the reader dies mid-read.
        self._read_refs: dict[str, dict[bytes, int]] = {}
        # Resource shapes of lease requests currently waiting for capacity,
        # reported in heartbeats as autoscaler demand (reference: resource
        # load in raylet heartbeats feeding autoscaler/v2).
        self._pending_lease_demand: dict[tuple, int] = {}
        # Unsealed creations per creator worker, force-deleted if the creator
        # dies between PlasmaCreate and PlasmaSeal (else the creator ref
        # leaks the arena bytes forever).
        self._creating: dict[bytes, str] = {}
        # TPU shares behind a device-release fence, per bundle key (None =
        # node-pool lease): bundle teardown withholds these from its
        # release; the fence re-grants them when the holder is dead.
        self._fence_pending: dict[tuple | None, float] = {}
        # TPU grants past the fence but not yet recorded on a worker's
        # lease_resources (spawn in progress): the grant fence must not
        # probe the device lock against these legitimate holders.
        self._tpu_grants_inflight: int = 0
        # Runtime-env-keyed forkservers (worker_zygote.py): env hash ->
        # zygote. Key "" (default env) is warmed at start; other keys
        # boot on first use and are LRU-bounded via _pool_keys.
        self._zygotes: dict[str, ZygoteHandle] = {}
        # Zygote-pool hot keys: env hash -> {"renv", "last_used"} in LRU
        # order (insertion order, re-inserted on touch). The maintenance
        # loop keeps zygote_pool_size idle workers per hot key; over
        # zygote_pool_max_keys the coldest key is evicted (zygote killed,
        # idle pooled workers of that env killed).
        self._pool_keys: dict[str, dict] = {}
        # Spawn-mode counters (debug_state + the pool smoke tests).
        self._spawn_stats = {"cold": 0, "pooled": 0}
        # --- object manager: push + prioritized pull admission ---------
        # In-progress inbound pushes: oid -> {offset, received, total,
        # data_size, meta_size} (receiver side of PushObject).
        self._receiving: dict[bytes, dict] = {}
        # Pull admission queue: heap-ordered (class, seq) waiters; classes
        # get(0) > wait(1) > task_arg(2) (reference pull_manager.h:51).
        self._pull_inflight = 0
        self._pull_waiters: list[dict] = []
        self._pull_seq = 0
        # Transfer counters (observability + the broadcast fan-out test).
        self.transfer_stats = {"chunks_served": 0, "pushes_served": 0,
                               "pulls_started": 0}
        # Preemption draining (resilience subsystem): after a GCE-style
        # preemption notice the node admits NO new leases, flushes its
        # task events, and — once the grace window expires — its workers
        # are killed and the GCS marks it dead. Timestamps ride the chaos
        # clock so VirtualClock runs measure the drain window virtually.
        self._draining = False
        self._draining_since = 0.0
        self._drain_reason = ""
        # GCE metadata preemption watcher (resilience/metadata_watcher),
        # started in start() behind config preempt_metadata_watch.
        self._metadata_watcher = None
        # Diagnostics counters (debug_state + the lease-wedge watchdog).
        self._wedge_events_total = 0
        self._oom_kills_total = 0
        self._orphan_leases_total = 0
        self._started_at = time.monotonic()
        # Lease-stage task events + spans (LEASED at grant, queue-wait and
        # spawn timings), flushed to the GCS on the worker flush cadence.
        from .task_events import TaskEventBuffer

        self._task_events = TaskEventBuffer(
            f"raylet-{self.node_id.hex()[:8]}", self.node_id.hex())

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        await self._server.start()
        reply = await self._gcs.call(
            "RegisterNode",
            {
                "node_id": self.node_id.hex(),
                "address": self.address,
                "object_store_path": self.store_path,
                "object_store_capacity": self.object_store_capacity,
                "resources": self.resources.to_dict(),
            },
        )
        if get_config().enable_worker_zygote:
            self._kick_zygote("")  # warm the default-env forkserver off-path
        self._tasks.append(spawn(self._heartbeat_loop()))
        self._tasks.append(spawn(self._worker_monitor_loop()))
        self._tasks.append(spawn(self._memory_monitor_loop()))
        self._tasks.append(spawn(self._debug_dump_loop()))
        self._tasks.append(spawn(self._lease_watchdog_loop()))
        self._tasks.append(spawn(self._task_event_flush_loop()))
        if get_config().log_to_driver:
            self._tasks.append(spawn(self._log_monitor_loop()))
        cfg = get_config()
        if cfg.preempt_metadata_watch:
            # GCE spot reclaim notice, straight from the node's own
            # metadata server into the PreemptionNotice drain path —
            # the watcher thread hops back onto the raylet loop.
            from ..resilience.metadata_watcher import (
                GceMetadataPreemptionWatcher)

            loop = asyncio.get_running_loop()
            self._metadata_watcher = GceMetadataPreemptionWatcher(
                lambda reason: loop.call_soon_threadsafe(
                    self.begin_draining, reason),
                url=cfg.preempt_metadata_url,
                poll_s=cfg.preempt_metadata_poll_s,
            ).start()
        for _ in range(cfg.num_prestart_workers):
            self._start_worker()

    @property
    def address(self) -> str:
        return self._server.address

    async def stop(self, graceful: bool = True) -> None:
        self._shutdown = True
        if self._metadata_watcher is not None:
            self._metadata_watcher._stop.set()  # no join: its thread may
            self._metadata_watcher = None       # be mid-poll; it's daemon
        for t in self._tasks:
            t.cancel()
        for w in self._workers.values():
            if w.proc is not None and w.proc.poll() is None:
                if graceful:
                    w.proc.terminate()
                else:
                    w.proc.kill()
        if graceful:
            await asyncio.sleep(0)
            deadline = time.monotonic() + 5.0
            for w in self._workers.values():
                if w.proc is not None:
                    try:
                        w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                    except Exception:
                        w.proc.kill()
        # Always reap after the kill escalation: a worker that survives
        # stop() keeps its exclusive libtpu device lock and crash-loops
        # whatever claims the chip next (serve-after-train handoff).
        for w in self._workers.values():
            if w.proc is None:
                continue
            if w.proc.poll() is None and not graceful:
                w.proc.kill()
            try:
                w.proc.wait(timeout=2)
            except Exception:
                pass
        for zh in self._zygotes.values():
            if zh.proc is not None:
                try:
                    zh.proc.kill()
                    zh.proc.wait(timeout=2)
                except Exception:
                    pass
                zh.proc = None
        self._zygotes.clear()
        await self._server.stop(grace=0.5 if graceful else 0.0)
        self.store.close()

    async def kill(self) -> None:
        """Abrupt node death (no drain, SIGKILL workers) — the GCS discovers
        it via failed health checks. Test-harness API (reference
        ``cluster_utils.py`` remove_node non-graceful path)."""
        await self.stop(graceful=False)

    def _store_stats(self) -> dict:
        """Store/spill accounting shared by heartbeats and debug_state
        (the node half of the memory observability layer)."""
        used = self.store.used()
        self._store_used_peak = max(self._store_used_peak, used)
        return {
            "used": used,
            "used_peak": self._store_used_peak,
            "capacity": self.object_store_capacity,
            "objects": self.store.num_objects(),
            "pinned_objects": len(self._pinned),
            "pinned_bytes": sum(self._pinned.values()),
            "spilled_objects": len(self._spilled),
            "spilled_bytes_total": self._spilled_bytes_total,
            "restored_bytes_total": self._restored_bytes_total,
            "spilled_objects_total": self._spilled_objects_total,
            "restored_objects_total": self._restored_objects_total,
            "fallback_allocations_total": self._fallback_allocations_total,
        }

    def _worker_rss(self) -> dict[str, int]:
        """RSS per tracked worker/driver process on this node."""
        from ..observability.memory import process_rss_bytes

        out: dict[str, int] = {}
        for w in self._workers.values():
            if w.pid and w.state != "dead":
                rss = process_rss_bytes(w.pid)
                if rss:
                    out[w.worker_id] = rss
        return out

    async def _heartbeat_loop(self) -> None:
        from ..observability.memory import hbm_stats

        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.health_check_period_ms / 1000.0)
            # Chaos injection point: the `preempt_slice` FaultPlan kind
            # delivers a GCE-style preemption notice at this node's Nth
            # heartbeat tick (deterministic per targeted node).
            if not self._draining and get_chaos().take_preempt_slice(
                    self.node_id.hex()):
                self.begin_draining("chaos: injected preemption notice")
            try:
                reply = await self._gcs.call(
                    "Heartbeat",
                    {
                        "node_id": self.node_id.hex(),
                        "draining": self._draining,
                        "drain_reason": self._drain_reason,
                        "drain_notice_clock": self._draining_since,
                        "resources": self.resources.to_dict(),
                        "pending_demand": [
                            {"shape": dict(shape), "count": count}
                            for shape, count in self._pending_lease_demand.items()
                        ],
                        # Store/spill/HBM/RSS gauges for the metrics
                        # pipeline (ray_tpu_object_store_* / ray_tpu_hbm_*).
                        "store": self._store_stats(),
                        "hbm": hbm_stats(),
                        "worker_rss_bytes": sum(self._worker_rss().values()),
                    },
                    timeout=5.0,
                )
                live_pgs = reply.get("live_pgs")
                if live_pgs is not None:
                    live = set(live_pgs)
                    now = time.monotonic()
                    for key, b in list(self._pg_bundles.items()):
                        # Age guard: a bundle reserved AFTER the GCS
                        # composed its live list would look orphaned for
                        # one beat — never reclaim fresh reservations.
                        if key[0] in live or now - b.get("reserved_at", 0.0) < 10.0:
                            continue
                        logger.info("reclaiming orphaned bundle %s", key)
                        self._drop_bundle(key)
                if reply.get("unknown"):
                    # The GCS restarted and lost the node table: re-register
                    # (gcs_client reconnection path in the reference).
                    logger.info("GCS does not know us — re-registering node %s",
                                self.node_id.hex()[:8])
                    await self._gcs.call(
                        "RegisterNode",
                        {
                            "node_id": self.node_id.hex(),
                            "address": self.address,
                            "object_store_path": self.store_path,
                            "object_store_capacity": self.object_store_capacity,
                            "resources": self.resources.to_dict(),
                        },
                        timeout=10.0,
                    )
                await self._refresh_node_table()
            except Exception:
                pass

    async def _worker_monitor_loop(self) -> None:
        """Detect worker process exits (reference: raylet detects via
        socket close; we poll pids). Actor-death reports that fail (e.g.
        the GCS is down) are queued and retried — a death observed during
        a GCS outage must still reach the restarted GCS, or the restored
        record stays ALIVE forever."""
        pending_deaths: list[dict] = []
        cfg = get_config()
        while True:
            await asyncio.sleep(0.2)
            # Zygote-pool maintenance (reference worker_pool prestart,
            # extended to runtime-env keys): keep a target of pre-forked
            # idle workers per hot env key so actor creation and task
            # bursts bind a ready, already-registered process instead of
            # paying spawn+register inline. The default env is always
            # hot; non-default keys are LRU-tracked in _pool_keys.
            if not self._shutdown and not self._draining:
                self._maintain_worker_pools(cfg)
            for w in list(self._workers.values()):
                # Drivers register without a proc handle but always live on
                # this host: poll their pid so a driver that exits with
                # unreleased pin_read refs (or mid-create objects) is reaped
                # like any worker — leaked read refs make objects
                # unspillable forever.
                if w.proc is None and w.state == "driver" and w.pid:
                    try:
                        os.kill(w.pid, 0)
                    except ProcessLookupError:
                        self._on_worker_dead(w)
                    except OSError:
                        pass  # EPERM etc: process exists
                    continue
                if w.proc is not None and w.proc.poll() is not None and w.state != "dead":
                    prev_state = w.state
                    self._on_worker_dead(w)
                    if prev_state == "dedicated" and w.actor_id:
                        pending_deaths.append({
                            "actor_id": w.actor_id,
                            # Incarnation identity: the GCS drops reports
                            # about a worker that is no longer the
                            # actor's current one (stale death after a
                            # restart already replaced it).
                            "worker_id": w.worker_id,
                            "reason": f"worker process exited with code {w.proc.returncode}",
                        })
            still_pending = []
            for report in pending_deaths:
                try:
                    await self._gcs.call("ReportActorDeath", report, timeout=5.0)
                except Exception:
                    still_pending.append(report)
            pending_deaths = still_pending
            # GC abandoned partial pushes: an unsealed receive allocation
            # with no progress (holder died, object never re-pulled) would
            # otherwise pin arena bytes forever — unsealed objects are not
            # spillable or evictable.
            now = time.monotonic()
            for oid, state in list(self._receiving.items()):
                if now - state["last_progress"] > cfg.object_receive_gc_grace_s:
                    self._receiving.pop(oid, None)
                    try:
                        self.store.delete(oid, force=True)
                    except Exception:
                        pass
                    self._object_meta.pop(oid, None)
                    logger.warning("reclaimed abandoned partial push of %s",
                                   oid.hex()[:12])

    def _pool_counts(self, env_hash: str) -> tuple[int, int]:
        """(idle, starting) workers of one env key."""
        idle = sum(
            1 for wid in self._idle
            if (w := self._workers.get(wid)) and w.env_hash == env_hash)
        starting = sum(
            1 for w in self._workers.values()
            if w.state == "starting" and w.env_hash == env_hash)
        return idle, starting

    def _maintain_worker_pools(self, cfg) -> None:
        """One maintenance tick: top idle pools up toward their targets.
        Refill rate is bounded per key (zygote_pool_refill_batch) and
        globally by the spawn-concurrency caps; never runs while
        draining (begin_draining stops the tick upstream) so a
        preempted node doesn't refill workers it is about to kill."""
        pool_size = cfg.zygote_pool_size if cfg.enable_worker_zygote else 0
        targets: list[tuple[str, dict | None, int]] = [
            ("", None, max(cfg.num_prestart_workers, pool_size))]
        for key, info in list(self._pool_keys.items()):
            targets.append((key, info.get("renv"), pool_size))
        for env_hash, renv, target in targets:
            if target <= 0:
                continue
            idle, starting = self._pool_counts(env_hash)
            cap = (max(cfg.maximum_startup_concurrency,
                       cfg.zygote_max_fork_concurrency)
                   if self._zygote_live(env_hash)
                   else cfg.maximum_startup_concurrency)
            want = min(target - idle - starting,
                       max(1, cfg.zygote_pool_refill_batch),
                       cap - starting)
            for _ in range(max(0, want)):
                try:
                    self._start_worker(renv)
                except Exception:
                    break
        self._shrink_idle_pools(cfg, {k: t for k, _r, t in targets})

    def _shrink_idle_pools(self, cfg, targets: dict[str, int]) -> None:
        """Idle worker killing (reference worker_pool
        ``idle_worker_killing_time_threshold_ms``): once a key's idle
        count exceeds its pool target, the LRU excess is reaped after
        the idle threshold — a burst that ballooned the pool must not
        leave hundreds of resident interpreters competing for CPU/RAM
        forever; re-spawning later is a ~ms zygote fork. 0 disables."""
        threshold_s = cfg.idle_worker_killing_time_threshold_ms / 1000.0
        if threshold_s <= 0:
            return
        now = time.monotonic()
        by_key: dict[str, list[WorkerHandle]] = {}
        for wid in self._idle:  # append-ordered: oldest idle first
            w = self._workers.get(wid)
            if w is not None:
                by_key.setdefault(w.env_hash, []).append(w)
        for key, idle_list in by_key.items():
            excess = len(idle_list) - targets.get(key, 0)
            for w in idle_list:
                if excess <= 0:
                    break
                if now - w.last_idle_time < threshold_s:
                    continue
                if w.proc is not None:
                    w.proc.terminate()
                self._on_worker_dead(w)
                excess -= 1

    def _release_lease(self, w: WorkerHandle) -> bool:
        """Release a worker's lease reservation. Returns True if a TPU
        device fence was started — the worker is being killed and must NOT
        go back to the idle pool (its process still holds the exclusive
        libtpu device lock; the TPU portion of the lease is re-granted only
        once the process is confirmed dead). Without the fence, the next
        TPU lease starts a worker that crash-loops on device init while the
        dying holder drains (the round-3 serve-after-train failure mode)."""
        if w.lease_resources.is_empty():
            return False
        lease, bundle_key = w.lease_resources, w.bundle_key
        w.lease_resources = ResourceSet()
        w.bundle_key = None
        tpu = lease.to_dict().get("TPU", 0.0)
        if tpu > 0 and w.proc is not None and w.proc.poll() is None and _in_loop():
            tpu_part = ResourceSet({"TPU": tpu})
            self._release_into(lease.subtract(tpu_part, allow_negative=True), bundle_key)
            self._fence_pending[bundle_key] = (
                self._fence_pending.get(bundle_key, 0.0) + tpu)
            try:
                w.proc.terminate()
            except Exception:
                pass
            spawn(self._fenced_tpu_release(w, tpu_part, bundle_key))
            return True
        self._release_into(lease, bundle_key)
        return False

    @staticmethod
    def _tpu_device_locked() -> bool:
        """Probe the host's libtpu device lock (an flock on
        ``/tmp/libtpu_lockfile``): True while some process — tracked
        worker or not — holds the chip. Read ``/proc/locks`` instead of
        flocking the file ourselves: even a momentary LOCK_EX|LOCK_NB
        probe could race a starting worker's own non-blocking libtpu
        acquisition and fail ITS device init — the exact crash this
        fence exists to prevent."""
        path = os.environ.get("RAY_TPU_LOCKFILE", "/tmp/libtpu_lockfile")
        try:
            st = os.stat(path)
        except OSError:
            return False  # no lockfile -> nobody has initialized a chip
        want = f"{os.major(st.st_dev):02x}:{os.minor(st.st_dev):02x}:{st.st_ino}"
        try:
            with open("/proc/locks") as f:
                for line in f:
                    # e.g. "1: FLOCK  ADVISORY  WRITE 1234 fd:00:5678 0 EOF"
                    parts = line.split()
                    if len(parts) >= 6 and parts[1] == "FLOCK" \
                            and parts[3] == "WRITE" and parts[5] == want:
                        return True
        except OSError:
            return False
        return False

    async def _await_tpu_grant_fence(self, request: ResourceSet) -> None:
        """GRANT-side TPU fence (complements the death-release fence in
        ``_fenced_tpu_release``): before handing out the node's FIRST
        outstanding TPU lease, wait for the libtpu device lock to be
        free. The release fence only covers workers this raylet tracks;
        the chip may still be held by an arbitrary process (a benchmark
        phase, a stray trainer) whose exit we cannot observe — without
        this probe the first replica after such a handoff crash-loops on
        device init. Skipped when a tracked worker already holds a TPU
        lease OR another TPU grant is mid-spawn (on multi-chip hosts the
        per-chip visibility envs mean the global lockfile probe would
        false-positive against a legitimate co-holder). Times out after
        ``tpu_grant_fence_timeout_s`` and grants anyway — the worker
        then retries exactly as before this fence existed."""
        if request.to_dict().get("TPU", 0.0) <= 0:
            return
        if self._tpu_grants_inflight > 0:
            return
        for w in self._workers.values():
            if w.lease_resources.to_dict().get("TPU", 0.0) > 0:
                return
        timeout = get_config().tpu_grant_fence_timeout_s
        deadline = time.monotonic() + timeout
        loop = asyncio.get_running_loop()
        while await loop.run_in_executor(None, self._tpu_device_locked):
            if time.monotonic() > deadline:
                logger.warning(
                    "TPU grant fence: device lock still held after %.0fs; "
                    "granting anyway", timeout)
                return
            await asyncio.sleep(0.25)

    def _release_into(self, res: ResourceSet, bundle_key: tuple | None) -> None:
        if res.is_empty():
            return
        if bundle_key is not None:
            b = self._pg_bundles.get(bundle_key)
            if b is not None:
                b["used"] = b["used"].subtract(res, allow_negative=True)
        else:
            self.resources.release(res)

    async def _fenced_tpu_release(self, w: WorkerHandle, tpu_part: ResourceSet,
                                  bundle_key: tuple | None) -> None:
        """Re-grant the TPU resource only after the previous holder's
        process is gone (SIGTERM already sent; escalate to SIGKILL at half
        the fence timeout). The kernel drops the libtpu flock on process
        death, so death == device released."""
        import functools

        loop = asyncio.get_running_loop()
        timeout = get_config().tpu_release_fence_timeout_s
        # Timed Popen.wait INSIDE the executor thread — an untimed wait
        # abandoned by wait_for would pin the shared executor thread
        # forever on an unkillable (D-state) worker.
        try:
            await loop.run_in_executor(
                None, functools.partial(w.proc.wait, timeout / 2))
        except Exception:
            try:
                w.proc.kill()
            except Exception:
                pass
            try:
                await loop.run_in_executor(
                    None, functools.partial(w.proc.wait, timeout / 2))
            except Exception:
                pass  # unkillable (D-state?): re-grant anyway after the fence
        left = self._fence_pending.get(bundle_key, 0.0) - tpu_part.get("TPU")
        if left > 0:
            self._fence_pending[bundle_key] = left
        else:
            self._fence_pending.pop(bundle_key, None)
        if bundle_key is not None and bundle_key not in self._pg_bundles:
            # The bundle was dropped mid-fence; _drop_bundle withheld our
            # share from its release, so hand it to the node pool directly.
            self.resources.release(tpu_part)
        else:
            self._release_into(tpu_part, bundle_key)
        self._wake_lease_waiters()

    def _on_worker_dead(self, w: WorkerHandle) -> None:
        w.state = "dead"
        if w.worker_id in self._idle:
            self._idle.remove(w.worker_id)
        self._release_lease(w)
        self._workers.pop(w.worker_id, None)
        for oid, count in self._read_refs.pop(w.worker_id, {}).items():
            for _ in range(count):
                self.store.release(oid)
        for oid, creator in list(self._creating.items()):
            if creator == w.worker_id:
                self.store.delete(oid, force=True)
                self._creating.pop(oid, None)
                self._object_meta.pop(oid, None)

    # ------------------------------------------------------------ worker pool
    @staticmethod
    def _env_hash(runtime_env: dict | None) -> str:
        renv = runtime_env or {}
        env_vars = renv.get("env_vars") or {}
        working_dir = renv.get("working_dir") or ""
        py_modules = renv.get("py_modules") or []
        pip = renv.get("pip") or renv.get("uv") or []
        # Interpreter-level plugins key the hash too: a conda/py_executable/
        # container task must NEVER match an idle default-interpreter worker
        # — that silently ran it on the wrong interpreter (and skipped the
        # plugin's setup-error surface entirely).
        interp = {k: renv.get(k)
                  for k in ("py_executable", "conda", "container", "image_uri")
                  if renv.get(k)}
        if (not env_vars and not working_dir and not py_modules and not pip
                and not interp):
            return ""
        import hashlib
        import json

        modules_digest = ""
        if py_modules:
            # Content-addressed, like the reference's uploaded py_modules
            # URIs: editing a module must produce a DIFFERENT env so stale
            # idle workers (old sys.path, old imports) never match.
            from .runtime_env import _hash_paths

            modules_digest = _hash_paths(list(py_modules))
        blob = json.dumps({"env_vars": env_vars, "working_dir": working_dir,
                           "py_modules": modules_digest, "pip": pip,
                           "interp": interp},
                          sort_keys=True, default=str)
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    # ------------------------------------------------------ worker zygote
    def _default_worker_env(self) -> dict:
        """The environment default-env workers run with (also the default
        zygote's own env, so its pre-imported image matches its children)."""
        env = dict(os.environ)
        env["PYTHONUNBUFFERED"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return env

    def _worker_env(self, runtime_env: dict | None) -> tuple[dict, str | None]:
        """(env, working_dir) a worker with ``runtime_env`` runs under —
        shared by direct spawns and env-keyed zygote boots so the zygote's
        pre-imported image is byte-equivalent to a cold spawn's."""
        env = dict(os.environ)
        # Worker stdout goes to a file the log monitor tails; without this
        # it would be 8KB block-buffered and prints from long-lived workers
        # would never reach the driver.
        env["PYTHONUNBUFFERED"] = "1"
        from .runtime_env import apply_runtime_env

        explicit_vars = (runtime_env or {}).get("env_vars") or {}
        if "JAX_PLATFORMS" not in explicit_vars:
            # Workers don't grab the TPU by default. FORCE cpu (don't
            # setdefault): drivers often run with JAX_PLATFORMS=axon/tpu
            # inherited from their own env, and passing that through made
            # every worker pay the multi-second accelerator-plugin boot
            # in sitecustomize (~9s/worker — the actor-creation
            # throughput collapse the perf suite exposed). A TPU worker
            # opts in by unsetting it via runtime_env env_vars.
            env["JAX_PLATFORMS"] = "cpu"
        # working_dir: tasks run with this cwd and import modules from it
        # (reference runtime_env working_dir, minus the remote upload —
        # single-host path semantics).
        working_dir = apply_runtime_env(env, runtime_env)
        if env.get("JAX_PLATFORMS") == "cpu" and "PALLAS_AXON_POOL_IPS" not in explicit_vars:
            # Some images hook accelerator-plugin registration (a multi-
            # second jax import) into sitecustomize, gated on this var.
            # CPU-only workers skip it: ~4s -> ~0.4s cold start. Runs AFTER
            # runtime_env (a TPU worker unsets JAX_PLATFORMS via env_vars
            # and needs the plugin boot) but never overrides an explicit
            # user-supplied value.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        if working_dir is not None and not os.path.isdir(working_dir):
            # Popen(cwd=missing) would raise AFTER the lease reserved
            # resources; run without the cwd instead — the task's import
            # error is visible, a leaked reservation is not.
            logger.warning("runtime_env working_dir %s does not exist; ignoring", working_dir)
            working_dir = None
        return env, working_dir

    @staticmethod
    def _zygote_eligible(runtime_env: dict | None) -> bool:
        """True when workers of this env may fork from an env-keyed
        zygote. Interpreter-level plugins can NEVER fork (a fork keeps
        this interpreter; conda/py_executable pick another binary and
        container wraps the whole command) — those envs always pay the
        cold spawn, the PR 1 enforcement path."""
        renv = runtime_env or {}
        return not any(renv.get(k) for k in
                       ("py_executable", "conda", "container", "image_uri"))

    def _boot_zygote(self, key: str) -> None:
        """Spawn the zygote for env ``key`` and wait for its post-import
        handshake. BLOCKING (interpreter boot + imports + runtime_env
        preparation) — runs in an executor thread, never on the event
        loop; ``zh.proc`` is published only once the handshake arrives,
        so spawns before that fall back to direct Popen."""
        import json

        zh = self._zygotes.get(key)
        if zh is None:
            return
        try:
            env, working_dir = self._worker_env(zh.renv)
            z = subprocess.Popen(
                [
                    sys.executable, "-m", "ray_tpu.core.worker_zygote",
                    "--raylet-address", self.address,
                    "--gcs-address", self.gcs_address,
                    "--node-id", self.node_id.hex(),
                    "--store-path", self.store_path,
                    "--store-capacity", str(self.object_store_capacity),
                ],
                env=env,
                cwd=working_dir,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=open(os.path.join(
                    self._session_dir,
                    f"zygote-{self.node_id.hex()[:12]}"
                    f"{'-' + key[:8] if key else ''}.err"), "ab"),
            )
            ready = json.loads(z.stdout.readline())
            if not ready.get("ready"):
                raise RuntimeError(f"unexpected zygote handshake {ready!r}")
            zh.proc = z
        except Exception as e:
            logger.warning("worker zygote (env %s) unavailable (%s); "
                           "using direct spawn", key or "default", e)
        finally:
            zh.booting = False

    def _kick_zygote(self, key: str, runtime_env: dict | None = None) -> None:
        """(Re)boot the zygote for env ``key`` off the event loop if it
        isn't running."""
        zh = self._zygotes.get(key)
        if zh is None:
            zh = self._zygotes[key] = ZygoteHandle(renv=runtime_env)
        if zh.booting:
            return
        if zh.proc is not None and zh.proc.poll() is None:
            return
        zh.proc = None
        zh.booting = True
        if _in_loop():
            asyncio.get_running_loop().run_in_executor(
                None, self._boot_zygote, key)
        else:
            self._boot_zygote(key)

    def _spawn_via_zygote(self, key: str, worker_id: str, log_path: str,
                          runtime_env: dict | None = None) -> int | None:
        import json
        import select

        zh = self._zygotes.get(key)
        if zh is None or zh.proc is None or zh.proc.poll() is not None:
            self._kick_zygote(key, runtime_env)  # warms up in the background
            return None  # this spawn goes direct
        req = {"worker_id": worker_id, "log": log_path,
               "env": {"RAY_TPU_WORKER_ID": worker_id}}
        z = zh.proc
        try:
            # The protocol lock serializes request/reply framing: pool
            # refills running in executor threads must not interleave
            # writes with a lease-path fork on the raylet loop.
            with zh.lock:
                z.stdin.write((json.dumps(req) + "\n").encode())
                z.stdin.flush()
                # Bounded wait: a wedged zygote must not stall the caller
                # (fork replies normally arrive in single-digit ms).
                ready, _, _ = select.select([z.stdout], [], [], 5.0)
                if not ready:
                    raise TimeoutError("zygote fork reply timed out")
                reply = json.loads(z.stdout.readline())
            return int(reply["pid"])
        except Exception as e:
            logger.warning("zygote fork failed (%s); using direct spawn", e)
            try:
                z.kill()
            except Exception:
                pass
            zh.proc = None
            return None

    def _touch_pool_key(self, env_hash: str, runtime_env: dict | None) -> None:
        """LRU-touch a non-default env key in the zygote pool: the
        maintenance loop keeps zygote_pool_size idle workers per hot key;
        over zygote_pool_max_keys the coldest key is evicted."""
        cfg = get_config()
        if (not env_hash or cfg.zygote_pool_size <= 0
                or not cfg.enable_worker_zygote
                or not self._zygote_eligible(runtime_env)):
            return
        self._pool_keys.pop(env_hash, None)
        self._pool_keys[env_hash] = {"renv": runtime_env,
                                     "last_used": time.monotonic()}
        while len(self._pool_keys) > max(1, cfg.zygote_pool_max_keys):
            self._evict_pool_key(next(iter(self._pool_keys)))

    def _evict_pool_key(self, env_hash: str) -> None:
        """Evict one env key from the pool: its zygote dies and its idle
        pooled workers are killed — a pooled worker is only ever handed
        to a lease with the SAME env hash, so mismatched residue is pure
        memory cost."""
        self._pool_keys.pop(env_hash, None)
        zh = self._zygotes.pop(env_hash, None)
        if zh is not None and zh.proc is not None:
            try:
                zh.proc.kill()
            except Exception:
                pass
        for wid in list(self._idle):
            w = self._workers.get(wid)
            if w is not None and w.env_hash == env_hash:
                if w.proc is not None:
                    w.proc.terminate()
                self._on_worker_dead(w)
        logger.info("zygote pool evicted env key %s", env_hash[:8])

    def _start_worker(self, runtime_env: dict | None = None) -> WorkerHandle:
        worker_id = WorkerID.from_random().hex()
        log_path = os.path.join(self._session_dir, f"worker-{worker_id[:12]}.out")
        env_hash = self._env_hash(runtime_env)
        if get_config().enable_worker_zygote and self._zygote_eligible(runtime_env):
            # Fork from the env-keyed warm zygote image (~ms) instead of
            # paying interpreter boot + imports per process. First use of
            # an env key boots its zygote in the background and this
            # spawn falls through to the direct (cold) path. (LRU touch
            # happens on the LEASE path, not here — pool refills must not
            # keep their own key artificially hot.)
            pid = self._spawn_via_zygote(env_hash, worker_id, log_path,
                                         runtime_env)
            if pid is not None:
                handle = WorkerHandle(worker_id=worker_id, pid=pid,
                                      proc=PidHandle(pid), env_hash=env_hash,
                                      spawn_mode="pooled",
                                      spawn_started_at=time.monotonic())
                handle.registered = (
                    asyncio.get_running_loop().create_future() if _in_loop() else None)
                self._workers[worker_id] = handle
                return handle
        env, working_dir = self._worker_env(runtime_env)
        env["RAY_TPU_WORKER_ID"] = worker_id
        from .runtime_env import resolve_python_executable, wrap_worker_command

        # Interpreter-level plugins: py_executable / conda pick the
        # worker's python; container wraps the whole command in
        # podman/docker. Failures raise BEFORE the Popen so the lease
        # reply carries the plugin's error, not a crash-looping worker.
        py = resolve_python_executable(runtime_env) or sys.executable
        cmd = wrap_worker_command(
            [
                py,
                "-m",
                "ray_tpu.core.worker_main",
                "--raylet-address",
                self.address,
                "--gcs-address",
                self.gcs_address,
                "--node-id",
                self.node_id.hex(),
                "--worker-id",
                worker_id,
                "--store-path",
                self.store_path,
                "--store-capacity",
                str(self.object_store_capacity),
            ],
            runtime_env,
        )
        proc = subprocess.Popen(
            cmd,
            env=env,
            cwd=working_dir,
            stdout=open(log_path, "wb"),
            stderr=subprocess.STDOUT,
        )
        handle = WorkerHandle(worker_id=worker_id, pid=proc.pid, proc=proc,
                              env_hash=env_hash, spawn_mode="cold",
                              spawn_started_at=time.monotonic())
        handle.registered = asyncio.get_running_loop().create_future() if _in_loop() else None
        self._workers[worker_id] = handle
        return handle

    async def handle_RegisterWorker(self, p: dict) -> dict:
        w = self._workers.get(p["worker_id"])
        if w is None:
            # Worker started externally (e.g. driver core worker) — track it.
            w = WorkerHandle(worker_id=p["worker_id"])
            self._workers[p["worker_id"]] = w
        w.address = p["address"]
        w.pid = p.get("pid", w.pid)
        if p.get("is_driver"):
            w.state = "driver"
            return {"node_id": self.node_id.hex()}
        if w.state == "starting":
            w.state = "idle"
            w.last_idle_time = time.monotonic()
            self._idle.append(w.worker_id)
            if w.spawn_started_at:
                # Spawn-to-register latency, the zygote pool's evidence
                # trail (cold Popen vs warm-image fork).
                _spawn_hist().observe(
                    (time.monotonic() - w.spawn_started_at) * 1000.0,
                    {"mode": w.spawn_mode})
                self._spawn_stats[w.spawn_mode] = (
                    self._spawn_stats.get(w.spawn_mode, 0) + 1)
                w.spawn_started_at = 0.0
        if w.registered is not None and not w.registered.done():
            w.registered.set_result(True)
        self._wake_lease_waiters()
        return {"node_id": self.node_id.hex()}

    def _zygote_live(self, env_hash: str) -> bool:
        zh = self._zygotes.get(env_hash)
        return (zh is not None and zh.proc is not None
                and zh.proc.poll() is None)

    async def _get_idle_worker(self, timeout: float, runtime_env: dict | None = None) -> WorkerHandle | None:
        """Pop an idle registered worker whose env matches, starting one if
        needed (reference: worker_pool runtime-env-hash matching)."""
        want = self._env_hash(runtime_env)
        self._touch_pool_key(want, runtime_env)
        deadline = time.monotonic() + timeout
        while True:
            for wid in list(self._idle):
                w = self._workers.get(wid)
                if w is None:
                    self._idle.remove(wid)
                    continue
                if w.proc is not None and w.proc.poll() is not None:
                    # Died while idle (e.g. OOM-killed between return and
                    # re-lease) — reap now rather than leasing a corpse.
                    self._on_worker_dead(w)
                    continue
                if w.state == "idle" and w.env_hash == want:
                    self._idle.remove(wid)
                    return w
            starting = sum(
                1 for w in self._workers.values()
                if w.state == "starting" and w.env_hash == want
            )
            cfg = get_config()
            # A live zygote makes spawns ~ms forks with no import storm:
            # allow a wider in-flight bound so a creation storm drains at
            # fork speed instead of queueing behind the cold-spawn cap.
            startup_cap = (max(cfg.maximum_startup_concurrency,
                               cfg.zygote_max_fork_concurrency)
                           if self._zygote_live(want)
                           else cfg.maximum_startup_concurrency)
            if starting < startup_cap:
                self._start_worker(runtime_env)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._lease_waiters.append(fut)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return None

    def _wake_lease_waiters(self) -> None:
        # Hand freed resources to parked admission waiters FIRST (in
        # priority+FIFO order), then wake idle-worker/bundle waiters.
        self._dispatch_admission()
        waiters, self._lease_waiters = self._lease_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(True)

    def _dispatch_admission(self) -> None:
        """Grant queued resource reservations in (priority, seq) order.
        Strict head-of-line: a request never overtakes an earlier one it
        could outrace — that race was the actor-creation starvation."""
        while self._admission_queue:
            entry = self._admission_queue[0]
            if entry["fut"].done():  # timed out / cancelled waiter
                self._admission_queue.pop(0)
                continue
            if not self.resources.can_fit(entry["request"]):
                break
            self.resources.acquire(entry["request"])
            self._admission_queue.pop(0)
            entry["fut"].set_result(True)

    async def _acquire_resources_queued(self, request: ResourceSet, priority: int, deadline: float) -> bool:
        """Reserve ``request`` against the node pool, waiting FIFO within
        priority class (0 = actor creation, 1 = normal tasks). Returns False
        on deadline. On True the reservation is held by the caller."""
        if not self._admission_queue and self.resources.can_fit(request):
            self.resources.acquire(request)
            return True
        self._admission_seq += 1
        entry = {
            "prio": priority,
            "seq": self._admission_seq,
            "request": request,
            "fut": asyncio.get_running_loop().create_future(),
            # Lease-wedge watchdog input — on the chaos clock so virtual
            # time replays the wedge thresholds deterministically.
            "enqueued_at": chaos_clock.now(),
        }
        # Insert in (priority, seq) order: earlier same-priority requests
        # stay ahead; higher-priority (lower number) requests go first.
        at = len(self._admission_queue)
        for i, e in enumerate(self._admission_queue):
            if (entry["prio"], entry["seq"]) < (e["prio"], e["seq"]):
                at = i
                break
        self._admission_queue.insert(at, entry)
        self._dispatch_admission()  # we may be admissible right now
        with self._track_demand(request):
            while not entry["fut"].done():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    try:
                        self._admission_queue.remove(entry)
                    except ValueError:
                        pass
                    # Lost race: granted between the deadline check and
                    # removal — keep the reservation and proceed.
                    return entry["fut"].done()
                try:
                    # Periodic re-dispatch guards against a missed wake.
                    await asyncio.wait_for(asyncio.shield(entry["fut"]), min(remaining, 0.5))
                except asyncio.TimeoutError:
                    self._dispatch_admission()
        return True

    @contextlib.contextmanager
    def _track_demand(self, request: ResourceSet):
        """Count this request's shape in `_pending_lease_demand` for the
        scope of a wait (heartbeats report it as autoscaler demand)."""
        shape = tuple(sorted(request.to_dict().items()))
        self._pending_lease_demand[shape] = self._pending_lease_demand.get(shape, 0) + 1
        try:
            yield
        finally:
            left = self._pending_lease_demand.get(shape, 1) - 1
            if left > 0:
                self._pending_lease_demand[shape] = left
            else:
                self._pending_lease_demand.pop(shape, None)

    # ---------------------------------------------------------- lease service
    async def _task_event_flush_loop(self) -> None:
        """Flush raylet-recorded task events/spans (LEASED, lease/spawn
        spans) to the GCS — the raylet's half of the worker flusher."""
        interval = get_config().task_events_flush_interval_ms / 1000.0
        while True:
            await asyncio.sleep(interval)
            events, dropped = self._task_events.drain()
            if not events and not dropped:
                continue
            try:
                await self._gcs.call(
                    "AddTaskEvents", {"events": events, "dropped": dropped},
                    timeout=10.0)
            except Exception:
                pass

    def _record_lease_grant(self, spec: dict, t_arrive: float,
                            queue_wait_ms: float, spawn_ms: float) -> None:
        """Record the LEASED transition (with the raylet-measured stage
        timings) and, when the spec is traced, the lease + worker-spawn
        spans — all fire-and-forget into the local event buffer."""
        task_id = spec.get("task_id") or b""
        if not task_id:
            return
        self._task_events.record(
            task_id, spec.get("name", ""), "LEASED", kind=spec.get("kind", 0),
            extra={"queue_wait_ms": round(queue_wait_ms, 3),
                   "spawn_ms": round(spawn_ms, 3),
                   "trace_id": spec.get("trace_id", "")})
        trace_id = spec.get("trace_id") or ""
        if not trace_id:
            return
        from ..observability import tracing

        now = time.time()
        start = now - (time.monotonic() - t_arrive)
        lease_span = tracing.make_span(
            f"lease {spec.get('name', '')}", "lease", start, now, trace_id,
            spec.get("span_id", ""),
            attrs={"queue_wait_ms": round(queue_wait_ms, 3),
                   "node_id": self.node_id.hex()})
        self._task_events.record_span(lease_span)
        if spawn_ms > 1.0:
            self._task_events.record_span(tracing.make_span(
                "worker spawn/setup", "lease", now - spawn_ms / 1000.0, now,
                trace_id, lease_span["span_id"],
                attrs={"node_id": self.node_id.hex()}))

    async def handle_RequestWorkerLease(self, p: dict) -> dict:
        """ClusterTaskManager::QueueAndScheduleTask equivalent
        (cluster_task_manager.cc:48): grant locally, or spill to a better
        node, or queue until resources free up."""
        spec = p["spec"]
        t_arrive = time.monotonic()
        request = self._lease_request_set(spec)
        grant_only_local = bool(p.get("grant_only_local") or p.get("dedicated"))

        # Draining (preemption notice): this node admits NOTHING new —
        # whatever it granted now would die inside the grace window.
        # Spill to a non-draining peer when one fits; otherwise refuse.
        if self._draining:
            if not grant_only_local:
                await self._refresh_node_table(max_age_s=0.45)
                node = (self._pick_remote_node(request, require_available=True)
                        or self._pick_remote_node(request))
                if node is not None:
                    return {"spillback": True, "node_address": node["address"],
                            "node_id": node["node_id"]}
            return {"granted": False,
                    "reason": "node draining (preemption notice)"}

        # Placement-group tasks run on the node holding their bundle and
        # draw resources from the bundle's reservation, not the node pool
        # (reference: bundle_scheduling_policy.cc, bundle resources are real).
        pg_id = spec.get("placement_group_id") or b""
        if pg_id:
            pg_hex = pg_id.hex() if isinstance(pg_id, bytes) else pg_id
            idx = spec.get("placement_group_bundle_index", -1)
            if not self._has_local_bundle(pg_hex, idx):
                target = await self._pg_bundle_node(pg_hex, idx)
                if target is None:
                    return {"granted": False, "reason": f"placement group {pg_hex} not created"}
                if target != self.node_id.hex():
                    node = self._node_table.get(target)
                    if node is None:
                        await self._refresh_node_table()
                        node = self._node_table.get(target)
                    if node is None:
                        return {"granted": False, "reason": "bundle node lost"}
                    return {"spillback": True, "node_address": node["address"], "node_id": target}
            return await self._grant_in_bundle(p, spec, pg_hex, idx)

        # Spread strategy: round-robin the lease over all feasible nodes
        # BEFORE considering local fit (policy/spread_scheduling_policy.cc);
        # otherwise lease pipelining would pack every task onto one node.
        strategy = spec.get("scheduling_strategy") or {}
        if strategy.get("type") == "spread" and not grant_only_local and not p.get("spilled"):
            from .scheduling import select_node_for_resources

            await self._refresh_node_table()
            pick = select_node_for_resources(
                self._node_table, self._lease_resources(spec), strategy
            )
            if pick is not None and pick != self.node_id.hex():
                node = self._node_table.get(pick)
                if node is not None:
                    return {"spillback": True, "node_address": node["address"], "node_id": pick}

        if not request.subset_of(self.resources.total):
            if grant_only_local:
                return {"granted": False, "reason": "infeasible on this node"}
            # Infeasible locally: wait (bounded) for a feasible peer — the
            # node table may be stale, a node may be joining, or the
            # autoscaler may launch one for the demand we report here.
            deadline = time.monotonic() + get_config().worker_register_timeout_s
            with self._track_demand(request):
                while True:
                    # Infeasible waiters SHARE one cached refresh per poll
                    # beat instead of each paying a GCS round trip.
                    await self._refresh_node_table(max_age_s=0.45)
                    node = self._pick_remote_node(request)
                    if node is not None:
                        return {"spillback": True, "node_address": node["address"], "node_id": node["node_id"]}
                    if time.monotonic() > deadline:
                        return {"granted": False, "reason": "infeasible everywhere"}
                    await asyncio.sleep(0.5)

        # Spillback decision before queuing (hybrid policy): if we cannot fit
        # now but another node can, send the lease there.
        if not self.resources.can_fit(request) and not grant_only_local:
            node = self._pick_remote_node(request, require_available=True)
            if node is not None and node["node_id"] != self.node_id.hex():
                return {"spillback": True, "node_address": node["address"], "node_id": node["node_id"]}

        # Reserve resources through the admission queue: actor creations
        # (dedicated leases) rank ahead of normal tasks, FIFO within class,
        # and the releaser grants directly to the head — no wake-and-race.
        deadline = time.monotonic() + get_config().worker_register_timeout_s
        priority = 0 if (p.get("dedicated") or spec.get("kind", 0) == 1) else 1
        if not await self._acquire_resources_queued(request, priority, deadline):
            return {"granted": False, "reason": "timed out waiting for resources"}
        queue_wait_ms = (time.monotonic() - t_arrive) * 1000.0

        inflight = False
        t_spawn = time.monotonic()
        try:
            await self._await_tpu_grant_fence(request)
            if request.to_dict().get("TPU", 0.0) > 0:
                self._tpu_grants_inflight += 1
                inflight = True
            worker = await self._get_idle_worker(
                get_config().worker_register_timeout_s, spec.get("runtime_env")
            )
        except Exception as e:
            self.resources.release(request)  # never leak the reservation
            return {"granted": False, "reason": f"worker start failed: {e}"}
        finally:
            if inflight:
                self._tpu_grants_inflight -= 1
        if worker is None:
            self.resources.release(request)
            return {"granted": False, "reason": "no worker available"}
        worker.lease_resources = request
        worker.state = "dedicated" if p.get("dedicated") else "leased"
        worker.lease_time = time.monotonic()
        worker.retriable = bool(spec.get("max_retries", 0)) and not p.get("dedicated")
        worker.lease_acked = False
        worker.lease_granted_at = chaos_clock.now()
        worker.orphan_probe = None
        if p.get("dedicated"):
            actor_id = spec.get("actor_id", b"")
            worker.actor_id = actor_id.hex() if isinstance(actor_id, bytes) else actor_id
        self._record_lease_grant(spec, t_arrive, queue_wait_ms,
                                 (time.monotonic() - t_spawn) * 1000.0)
        self._maybe_chaos_kill_lease(worker)
        extras = self._try_extra_grants(p, spec, request)
        self._wake_lease_waiters()
        reply = {
            "granted": True,
            "worker_id": worker.worker_id,
            "worker_address": worker.address,
            "node_id": self.node_id.hex(),
        }
        if extras:
            reply["extra_grants"] = extras
        return reply

    def _try_extra_grants(self, p: dict, spec: dict,
                          request: ResourceSet) -> list[dict]:
        """Best-effort additional grants for a multiplexed lease request
        (``num_workers`` > 1: the owner's queue is deep). Only workers
        that are idle RIGHT NOW with a matching env, and resources that
        fit without queuing, are granted — anything slower would delay
        the primary reply — and nothing is granted past parked admission
        waiters (they reserved their place in line first). LEASED task
        events are NOT recorded here: the owner stamps LEASED at dispatch
        for every task it pushes onto a multiplexed lease, exactly as it
        does for reused leases, so per-task records stay identical to the
        one-lease-per-RPC path."""
        want = min(int(p.get("num_workers") or 1), 64) - 1
        extras: list[dict] = []
        if want <= 0 or p.get("dedicated"):
            return extras
        env_hash = self._env_hash(spec.get("runtime_env"))
        while len(extras) < want:
            if self._admission_queue or not self.resources.can_fit(request):
                break
            w = None
            for wid in list(self._idle):
                cand = self._workers.get(wid)
                if cand is None:
                    self._idle.remove(wid)
                    continue
                if cand.proc is not None and cand.proc.poll() is not None:
                    self._on_worker_dead(cand)
                    continue
                if cand.state == "idle" and cand.env_hash == env_hash:
                    self._idle.remove(wid)
                    w = cand
                    break
            if w is None:
                # No idle worker: warm the pool for the NEXT request, but
                # never block this reply on a spawn.
                starting = sum(1 for x in self._workers.values()
                               if x.state == "starting" and x.env_hash == env_hash)
                if starting < get_config().maximum_startup_concurrency:
                    try:
                        self._start_worker(spec.get("runtime_env"))
                    except Exception:
                        pass
                break
            self.resources.acquire(request)
            w.lease_resources = request
            w.state = "leased"
            w.lease_time = time.monotonic()
            w.retriable = bool(spec.get("max_retries", 0))
            w.lease_acked = False
            w.lease_granted_at = chaos_clock.now()
            w.orphan_probe = None
            self._maybe_chaos_kill_lease(w)
            extras.append({"worker_id": w.worker_id,
                           "worker_address": w.address})
        return extras

    def _maybe_chaos_kill_lease(self, worker: WorkerHandle) -> None:
        """Chaos injection point: SIGKILL the worker of the lease just
        granted (kill-on-Nth-lease FaultPlan rule) — the owner's task push
        fails and the retry / actor-restart machinery takes over."""
        if worker.proc is None:
            return
        if not get_chaos().take_kill_on_lease(self.node_id.hex()):
            return
        logger.warning("chaos: killing worker %s (pid %d) of the lease just "
                       "granted", worker.worker_id[:12], worker.pid)
        try:
            worker.proc.kill()
        except Exception:
            pass

    async def _grant_in_bundle(self, p: dict, spec: dict, pg_hex: str, idx: int) -> dict:
        """Lease a worker whose resources are charged against a committed
        bundle's reservation (so bundles cannot be oversubscribed)."""
        res = dict(spec.get("resources") or {})
        if not res:
            res = {"CPU": 1.0}
        request = ResourceSet(res)
        t_arrive = time.monotonic()
        deadline = time.monotonic() + get_config().worker_register_timeout_s
        key = None
        while True:
            key = self._pick_bundle(pg_hex, idx, request)
            if key is not None:
                b = self._pg_bundles[key]
                b["used"] = b["used"].add(request)
                break
            if time.monotonic() > deadline:
                return {"granted": False, "reason": f"bundle {pg_hex}[{idx}] has no spare capacity for {res}"}
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._lease_waiters.append(fut)
            try:
                await asyncio.wait_for(fut, 0.5)
            except asyncio.TimeoutError:
                pass
        queue_wait_ms = (time.monotonic() - t_arrive) * 1000.0
        inflight = False
        t_spawn = time.monotonic()
        try:
            await self._await_tpu_grant_fence(request)
            if request.to_dict().get("TPU", 0.0) > 0:
                self._tpu_grants_inflight += 1
                inflight = True
            worker = await self._get_idle_worker(
                get_config().worker_register_timeout_s, spec.get("runtime_env")
            )
        except Exception as e:
            worker = None
            reason = f"worker start failed: {e}"
        else:
            reason = "no worker available"
        finally:
            if inflight:
                self._tpu_grants_inflight -= 1
        if worker is None:
            b = self._pg_bundles.get(key)
            if b is not None:
                b["used"] = b["used"].subtract(request, allow_negative=True)
            return {"granted": False, "reason": reason}
        self._record_lease_grant(spec, t_arrive, queue_wait_ms,
                                 (time.monotonic() - t_spawn) * 1000.0)
        worker.lease_resources = request
        worker.bundle_key = key
        worker.state = "dedicated" if p.get("dedicated") else "leased"
        worker.lease_time = time.monotonic()
        worker.retriable = bool(spec.get("max_retries", 0)) and not p.get("dedicated")
        worker.lease_acked = False
        worker.lease_granted_at = chaos_clock.now()
        worker.orphan_probe = None
        if p.get("dedicated"):
            actor_id = spec.get("actor_id", b"")
            worker.actor_id = actor_id.hex() if isinstance(actor_id, bytes) else actor_id
        self._maybe_chaos_kill_lease(worker)
        self._wake_lease_waiters()
        return {
            "granted": True,
            "worker_id": worker.worker_id,
            "worker_address": worker.address,
            "node_id": self.node_id.hex(),
        }

    def _pick_bundle(self, pg_hex: str, idx: int, request: ResourceSet) -> tuple | None:
        """Find a committed local bundle with spare capacity for `request`."""
        for key, b in self._pg_bundles.items():
            if key[0] != pg_hex or not b.get("committed"):
                continue
            if idx >= 0 and key[1] != idx:
                continue
            spare = b["resources"].subtract(b["used"], allow_negative=True)
            if request.subset_of(spare):
                return key
        return None

    def _has_local_bundle(self, pg_hex: str, idx: int) -> bool:
        if idx >= 0:
            b = self._pg_bundles.get((pg_hex, idx))
            return bool(b and b.get("committed"))
        return any(
            k[0] == pg_hex and b.get("committed") for k, b in self._pg_bundles.items()
        )

    async def _pg_bundle_node(self, pg_hex: str, idx: int) -> str | None:
        try:
            reply = await self._gcs.call("GetPlacementGroup", {"pg_id": pg_hex}, timeout=5.0)
        except Exception:
            return None
        pg = reply.get("pg") or {}
        locations = pg.get("bundle_locations") or []
        if not locations:
            return None
        if idx >= 0:
            return locations[idx] if idx < len(locations) else None
        return locations[0]

    def _lease_resources(self, spec: dict) -> dict:
        res = dict(spec.get("resources") or {})
        if not res and spec.get("kind", 0) == 0:
            res = {"CPU": 1.0}
        return res

    def _lease_request_set(self, spec: dict) -> ResourceSet:
        """Cached fixed-point ResourceSet for a lease request's shape.
        Safe to share: ResourceSet algebra never mutates in place (every
        acquire/release builds a new set), so N requests and N worker
        ``lease_resources`` fields may all alias one object."""
        res = self._lease_resources(spec)
        key = tuple(sorted(res.items()))
        cached = self._request_shape_cache.get(key)
        if cached is None:
            if len(self._request_shape_cache) > 256:
                self._request_shape_cache.clear()
            cached = self._request_shape_cache[key] = ResourceSet(res)
        return cached

    async def _refresh_node_table(self, max_age_s: float = 0.0) -> None:
        """GetAllNodes into the local cache. Concurrent refreshers share
        ONE in-flight RPC, and ``max_age_s`` > 0 accepts a recent-enough
        cache outright — N parked infeasible-lease waiters used to each
        fire their own GCS round trip every 0.5 s poll beat."""
        if max_age_s > 0 and time.monotonic() - self._node_table_ts < max_age_s:
            return
        if self._node_table_refresh is not None:
            await asyncio.shield(self._node_table_refresh)
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._node_table_refresh = fut
        try:
            nodes = await self._gcs.call("GetAllNodes", {}, timeout=5.0)
            self._node_table = {n["node_id"]: n for n in nodes["nodes"]}
            self._node_table_ts = time.monotonic()
        except Exception:
            pass
        finally:
            self._node_table_refresh = None
            if not fut.done():
                fut.set_result(None)

    def _pick_remote_node(self, request: ResourceSet, require_available: bool = False) -> dict | None:
        best = None
        for node_id, node in self._node_table.items():
            if node_id == self.node_id.hex() or node.get("state") != "ALIVE" \
                    or node.get("draining"):
                continue
            nr = NodeResources.from_dict(node["resources"])
            if require_available and not nr.can_fit(request):
                continue
            if not request.subset_of(nr.total):
                continue
            if best is None or nr.utilization() < best[1]:
                best = (node, nr.utilization())
        return best[0] if best else None

    async def handle_PinLoopWorker(self, p: dict) -> dict:
        """Mark/unmark the worker hosting ``actor_id`` as parking a
        resident compiled-loop executor (exempt from orphan-lease
        reclaim — see WorkerHandle.loop_pinned)."""
        actor_id = p.get("actor_id") or ""
        pinned = bool(p.get("pinned", True))
        for w in self._workers.values():
            if actor_id and w.actor_id == actor_id and w.state != "dead":
                w.loop_pinned = pinned
                return {"ok": True, "worker_id": w.worker_id}
        return {"ok": False}

    async def handle_AckLease(self, p: dict) -> dict:
        """Owner (or the GCS, for dedicated leases) confirms it received
        the grant reply. Un-acked leases past ``lease_orphan_timeout_s``
        are reclaimed by the watchdog — a grant whose reply was lost in
        transit otherwise strands its reservation forever (the ROADMAP-1c
        lease-timeout cascade)."""
        ids = list(p.get("worker_ids") or ())
        if p.get("worker_id"):
            ids.append(p["worker_id"])
        for wid in ids:
            w = self._workers.get(wid)
            if w is not None:
                w.lease_acked = True
        return {}

    async def handle_ReturnWorker(self, p: dict) -> dict:
        w = self._workers.get(p["worker_id"])
        if w is None or w.state == "dead":
            return {}
        if self._release_lease(w):
            # TPU device fence: the worker was killed and must not rejoin
            # the idle pool; the TPU re-grant happens when it is dead.
            self._on_worker_dead(w)
            self._wake_lease_waiters()
            return {}
        if w.proc is not None and w.proc.poll() is not None:
            self._on_worker_dead(w)
            self._wake_lease_waiters()
            return {}
        if p.get("kill"):
            if w.proc is not None:
                w.proc.terminate()
            self._on_worker_dead(w)
        else:
            w.state = "idle"
            w.actor_id = ""
            w.last_idle_time = time.monotonic()
            self._idle.append(w.worker_id)
        self._wake_lease_waiters()
        return {}

    async def handle_HealthCheck(self, p: dict) -> dict:
        return {"node_id": self.node_id.hex()}

    # ------------------------------------------------------------- preemption
    async def handle_PreemptionNotice(self, p: dict) -> dict:
        """GCE-style preemption notice delivered over RPC (the instance
        manager / test harness path; the chaos engine delivers the same
        notice in-process via ``take_preempt_slice``)."""
        started = self.begin_draining(
            p.get("reason") or "preemption notice",
            grace_s=p.get("grace_s"))
        return {"draining": True, "started": started,
                "node_id": self.node_id.hex()}

    def begin_draining(self, reason: str, grace_s: float | None = None) -> bool:
        """Enter the draining state: no new leases are admitted (requests
        spill to non-draining peers), buffered task events are flushed,
        the GCS is told to flag the node and publish ``node_preempted``,
        and after the grace window the workers are killed and the node is
        reported dead. Must run on the raylet loop."""
        if self._draining or self._shutdown:
            return False
        self._draining = True
        self._draining_since = chaos_clock.now()
        self._drain_reason = reason
        logger.warning("node %s draining (%s): refusing new leases, dying in "
                       "%.1fs grace", self.node_id.hex()[:8], reason,
                       get_config().preempt_grace_s if grace_s is None
                       else float(grace_s))
        self._tasks.append(spawn(self._drain_to_death(grace_s)))
        return True

    async def _drain_to_death(self, grace_s: float | None) -> None:
        grace = (get_config().preempt_grace_s if grace_s is None
                 else float(grace_s))
        # Flush buffered task events NOW — after the VM reclaim nothing
        # ships them, and the whole point of the drain is that no
        # observability is lost to the preemption.
        events, dropped = self._task_events.drain()
        try:
            if events or dropped:
                await self._gcs.call(
                    "AddTaskEvents", {"events": events, "dropped": dropped},
                    timeout=10.0)
        except Exception:
            pass
        try:
            await self._gcs.call("ReportNodeDraining", {
                "node_id": self.node_id.hex(),
                "reason": self._drain_reason,
                "grace_s": grace,
                "notice_clock": self._draining_since,
            }, timeout=10.0)
        except Exception:
            pass
        await chaos_clock.sleep(grace)
        if self._shutdown:
            return
        logger.warning("preemption grace expired on node %s: reclaiming "
                       "(killing %d workers)", self.node_id.hex()[:8],
                       len(self._workers))
        for w in list(self._workers.values()):
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        try:
            await self._gcs.call("NodePreempted", {
                "node_id": self.node_id.hex(),
                "reason": self._drain_reason,
            }, timeout=10.0)
        except Exception:
            pass

    # ----------------------------------------------------------- spill manager
    def _create_with_spill(self, oid: bytes, data_size: int, meta_size: int) -> int:
        """Allocate, spilling pinned primaries to disk if LRU eviction of
        secondary copies wasn't enough (local_object_manager.cc
        SpillObjectsOfSize)."""
        try:
            offset = self.store.create(oid, data_size, meta_size)
        except StoreFullError:
            self._spill_objects(data_size + meta_size)
            offset = self.store.create(oid, data_size, meta_size)
            self._fallback_allocations_total += 1
        self._store_used_peak = max(self._store_used_peak, self.store.used())
        return offset

    def _spill_objects(self, nbytes: int) -> int:
        """Move the oldest unreferenced pinned objects out of shm until
        ~`nbytes` are free. Space is reclaimed synchronously (callers need
        it now); the disk write itself is offloaded to an executor thread so
        the event loop — heartbeats, leases — never stalls on file I/O
        (reference: spill runs in dedicated IO workers). Until the write
        completes the blob is served from ``_spill_pending``."""
        freed = 0
        for oid in list(self._pinned):
            if freed >= nbytes:
                break
            if self.store.contains(oid) != 2 or self.store.ref_count(oid) > 0:
                continue  # mid-read or unsealed: not spillable right now
            info = self.store.get_info(oid)
            if info is None:
                self._pinned.pop(oid, None)
                continue
            offset, data_size, meta_size = info
            blob = bytes(self.store.read(offset, data_size + meta_size))
            self.store.unpin(oid)
            self.store.delete(oid, force=False)
            self._pinned.pop(oid, None)
            self._spilled[oid] = (data_size, meta_size)
            self._spill_pending[oid] = blob
            if _in_loop():
                spawn(self._write_spill_file(oid, blob))
            else:
                try:
                    self._write_file(self._spill_path(oid), blob)
                    self._spill_pending.pop(oid, None)
                except OSError as e:
                    # Disk write failed (full disk / chaos injection): the
                    # blob stays in _spill_pending, so the object remains
                    # restorable from memory — degraded, never lost.
                    logger.warning("spill write of %s failed: %s "
                                   "(kept in memory)", oid.hex()[:12], e)
            self._spilled_bytes_total += data_size + meta_size
            self._spilled_objects_total += 1
            meta = self._object_meta.get(oid)
            if meta is not None:
                meta["spilled"] = True
            freed += data_size + meta_size
        return freed

    def _spill_path(self, oid: bytes) -> str:
        return os.path.join(self._spill_dir, oid.hex())

    def _write_file(self, path: str, blob: bytes) -> None:
        if get_chaos().maybe_fail_spill():
            raise OSError("chaos-injected spill write failure")
        os.makedirs(self._spill_dir, exist_ok=True)
        with open(path, "wb") as f:
            f.write(blob)

    async def _write_spill_file(self, oid: bytes, blob: bytes) -> None:
        path = self._spill_path(oid)
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self._write_file, path, blob)
        except OSError as e:
            # Failed disk write: keep the blob in _spill_pending — restore
            # serves it from memory, and a later spill pass may re-spill it.
            logger.warning("spill write of %s failed: %s (kept in memory)",
                           oid.hex()[:12], e)
            return
        # Identity check: a restore + re-spill while we were writing installs
        # a new pending blob (and its own write task) — leave those alone.
        if self._spill_pending.get(oid) is blob:
            self._spill_pending.pop(oid, None)
        if oid not in self._spilled:
            # Deleted or restored while the write was in flight.
            try:
                os.unlink(path)
            except OSError:
                pass

    async def _restore_spilled(self, oid: bytes) -> bool:
        """Bring a spilled object back into shm (restore-on-Get,
        local_object_manager.cc AsyncRestoreSpilledObject)."""
        sizes = self._spilled.get(oid)
        if sizes is None:
            return False
        data_size, meta_size = sizes
        blob = self._spill_pending.get(oid)
        if blob is None:
            path = self._spill_path(oid)
            loop = asyncio.get_running_loop()
            try:
                blob = await loop.run_in_executor(None, lambda: open(path, "rb").read())
            except OSError:
                return False
        if oid not in self._spilled:
            return True  # a concurrent handler restored it during the read
        offset = self._create_with_spill(oid, data_size, meta_size)
        self.store.write(offset, blob)
        self.store.seal(oid)
        self.store.pin(oid)
        self.store.release(oid)
        self._pinned[oid] = data_size + meta_size
        self._spilled.pop(oid, None)
        self._spill_pending.pop(oid, None)
        self._restored_bytes_total += data_size + meta_size
        self._restored_objects_total += 1
        meta = self._object_meta.get(oid)
        if meta is not None:
            meta["spilled"] = False
        try:
            os.unlink(self._spill_path(oid))
        except OSError:
            pass
        return True

    async def _log_monitor_loop(self) -> None:
        """Tail this node's worker log files and forward new lines to the
        GCS log channel (reference ``log_monitor.py``: per-node agent
        tailing worker logs for the driver)."""
        import glob

        offsets: dict[str, int] = {}
        period = get_config().log_monitor_poll_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            batch = []
            staged: dict[str, int] = {}  # offsets commit only after publish
            for path in glob.glob(os.path.join(self._session_dir, "worker-*.out")):
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                start = offsets.get(path, 0)
                if size <= start:
                    continue
                try:
                    with open(path, "rb") as f:
                        f.seek(start)
                        chunk = f.read(min(size - start, 256 * 1024))
                except OSError:
                    continue
                # forward whole lines only; carry partial tails to next tick
                cut = chunk.rfind(b"\n") + 1
                if cut == 0:
                    if len(chunk) < 256 * 1024:
                        continue
                    cut = len(chunk)  # giant single line: forward truncated
                worker_tag = os.path.basename(path)[len("worker-"):-len(".out")]
                lines = chunk[:cut].decode("utf-8", errors="replace").splitlines()
                batch.append({"worker": worker_tag, "lines": lines})
                staged[path] = start + cut
            if batch:
                try:
                    await self._gcs.call(
                        "PublishLogs",
                        {"node_id": self.node_id.hex(), "batch": batch},
                        timeout=5.0,
                    )
                except Exception:
                    continue  # don't commit offsets: re-read and retry next tick
                offsets.update(staged)

    async def _memory_monitor_loop(self) -> None:
        """Two duties of the reference's memory safety net: proactive spill
        above ``object_spilling_threshold`` (local_object_manager.cc) and the
        node memory watcher that OOM-kills the newest retriable lease
        (memory_monitor.h:52, worker_killing_policy.cc)."""
        cfg = get_config()
        if not cfg.memory_monitor_refresh_ms:
            return
        period = cfg.memory_monitor_refresh_ms / 1000.0
        while True:
            await chaos_clock.sleep(period)
            try:
                threshold = int(self.object_store_capacity * cfg.object_spilling_threshold)
                if self.store.used() > threshold:
                    self._spill_objects(self.store.used() - threshold)
                usage = self._memory_usage_fn()
                # Cooldown: give the kernel time to reap the last victim and
                # publish the freed memory before killing again (reference
                # memory monitor min-interval between kills).
                if usage > cfg.memory_usage_threshold and (
                    time.monotonic() - self._last_oom_kill > max(1.0, 4 * period)
                ):
                    if self._oom_kill_one(usage):
                        self._last_oom_kill = time.monotonic()
            except Exception:
                logger.exception("memory monitor iteration failed")

    def _oom_kill_one(self, usage: float) -> bool:
        victims = [
            w for w in self._workers.values()
            if w.state in ("leased", "dedicated") and w.proc is not None and w.retriable
        ]
        if not victims:
            return False
        victim = max(victims, key=lambda w: w.lease_time)
        logger.warning(
            "Node memory usage %.0f%% above threshold: killing newest retriable "
            "lease (worker %s, pid %d) — the owner will retry it",
            usage * 100, victim.worker_id[:12], victim.pid,
        )
        try:
            victim.proc.kill()
        except Exception:
            pass
        self._oom_kills_total += 1
        from ..diagnostics.errors import make_event

        spawn(self._publish_error_event(make_event(
            "oom_kill",
            f"node memory usage {usage * 100:.0f}% above threshold: killed "
            f"newest retriable lease (worker {victim.worker_id[:12]}, "
            f"pid {victim.pid})",
            source="raylet", node_id=self.node_id.hex(),
            worker_id=victim.worker_id, actor_id=victim.actor_id)))
        return True

    # ------------------------------------------------------- plasma service
    async def handle_PlasmaCreate(self, p: dict) -> dict:
        from ..native.store import ObjectExistsError

        oid = p["id"]
        try:
            offset = self._create_with_spill(oid, p["data_size"], p.get("meta_size", 0))
        except StoreFullError as e:
            return {"error": "store_full", "detail": str(e)}
        except ObjectExistsError:
            # Deterministic return IDs: a retried task recreates the same
            # object. Sealed (in shm or on disk) → idempotent success.
            # Unsealed with a dead creator → reclaim and recreate.
            if self.store.contains(oid) == 2 or oid in self._spilled:
                return {"exists": True}
            # `_workers` covers raylet-spawned workers AND drivers (both
            # register; dead drivers are reaped by the pid monitor), so a
            # live creator of either kind is recognized here.
            creator = self._creating.get(oid)
            if creator is not None and creator in self._workers:
                return {"error": "create_conflict",
                        "detail": f"{oid.hex()} is being created by a live worker"}
            self.store.delete(oid, force=True)
            try:
                offset = self._create_with_spill(oid, p["data_size"], p.get("meta_size", 0))
            except StoreFullError as e:
                return {"error": "store_full", "detail": str(e)}
        if p.get("creator"):
            self._creating[oid] = p["creator"]
        self._object_meta[oid] = {"size": p["data_size"] + p.get("meta_size", 0)}
        return {"offset": offset}

    async def handle_PlasmaSeal(self, p: dict) -> dict:
        """Seal + pin: objects sealed through the RPC service are primary
        copies (created on this node by their owner) and must survive until
        deleted — spilled under pressure, never silently evicted."""
        oid = p["id"]
        self.store.seal(oid)
        self.store.pin(oid)
        self.store.release(oid)
        self._creating.pop(oid, None)
        meta = self._object_meta.get(oid)
        self._pinned[oid] = meta["size"] if meta else 0
        fut = self._fetching.pop(oid, None)
        if fut is not None and not fut.done():
            fut.set_result(True)
        return {}

    async def handle_PlasmaGetInfo(self, p: dict) -> dict:
        """Return (offset, sizes) for a sealed local object; if absent and an
        owner address is supplied, pull it from a remote node first
        (PullManager, pull_manager.h:51)."""
        oid: bytes = p["id"]
        timeout = p.get("timeout", 0)
        deadline = time.monotonic() + (timeout if timeout else 0)
        while True:
            info = self.store.get_info(oid)
            if info is None and oid in self._spilled:
                try:
                    await self._restore_spilled(oid)
                except StoreFullError:
                    pass  # shm full of read-pinned objects: poll until free
                info = self.store.get_info(oid)
            if info is not None:
                if p.get("pin_read"):
                    # Hold a store ref for the reader so the object cannot be
                    # spilled/evicted while its views are alive; the reader
                    # sends PlasmaRelease when the value is GC'd.
                    self.store.add_ref(oid)
                    reader = p.get("reader") or ""
                    refs = self._read_refs.setdefault(reader, {})
                    refs[oid] = refs.get(oid, 0) + 1
                return {"found": True, "offset": info[0], "data_size": info[1], "meta_size": info[2]}
            if p.get("owner_address"):
                pulled = await self._maybe_pull(
                    oid, p["owner_address"], p.get("pull_class", "get"))
                if pulled:
                    continue
            if timeout == 0 or time.monotonic() > deadline:
                return {"found": False}
            await asyncio.sleep(0.02)

    _PULL_CLASS = {"get": 0, "wait": 1, "task_arg": 2}

    async def _admit_pull(self, pull_class: str) -> None:
        """Pull admission control: bounded concurrent inbound transfers,
        ordered get > wait > task-arg within the queue (reference
        pull_manager.h:51 — a user blocked in ray.get outranks a
        prefetching task-arg pull)."""
        cfg = get_config()
        if (not self._pull_waiters
                and self._pull_inflight < cfg.pull_manager_max_concurrent):
            self._pull_inflight += 1
            return
        self._pull_seq += 1
        entry = {
            "key": (self._PULL_CLASS.get(pull_class, 2), self._pull_seq),
            "fut": asyncio.get_running_loop().create_future(),
        }
        self._pull_waiters.append(entry)
        self._pull_waiters.sort(key=lambda e: e["key"])
        await entry["fut"]

    def _release_pull(self) -> None:
        self._pull_inflight -= 1
        while (self._pull_waiters
               and self._pull_inflight < get_config().pull_manager_max_concurrent):
            entry = self._pull_waiters.pop(0)
            if entry["fut"].done():
                continue
            self._pull_inflight += 1
            entry["fut"].set_result(True)

    async def _maybe_pull(self, oid: bytes, owner_address: str,
                          pull_class: str = "get") -> bool:
        """Locate via the owner (OwnershipBasedObjectDirectory) and
        transfer from a holder node: ask the holder to PUSH (holder-driven
        pipelined chunks, push_manager.h:30), falling back to puller-driven
        chunk fetches. A completed copy is reported back to the owner so
        LATER pullers of the same object fan out across receivers instead
        of all draining the primary (broadcast tree)."""
        fut = self._fetching.get(oid)
        if fut is not None:
            try:
                await asyncio.wait_for(asyncio.shield(fut), 30.0)
            except asyncio.TimeoutError:
                return False
            return True
        fut = asyncio.get_running_loop().create_future()
        self._fetching[oid] = fut
        await self._admit_pull(pull_class)
        self.transfer_stats["pulls_started"] += 1
        try:
            owner = RpcClient(owner_address)
            status = await owner.call(
                "GetObjectLocations", {"id": oid},
                timeout=get_config().object_directory_rpc_timeout_s)
            locations = [n for n in status.get("locations", []) if n != self.node_id.hex()]
            # Fan-out: prefer SECONDARY holders (earlier receivers) over
            # the primary, rotating among them by a node-local stamp — a
            # broadcast then drains receivers tree-style instead of every
            # puller queueing on the one primary.
            primary = status.get("primary", "")
            secondaries = [n for n in locations if n != primary]
            if len(secondaries) > 1:
                k = int(self.node_id.hex()[:4], 16) % len(secondaries)
                secondaries = secondaries[k:] + secondaries[:k]
            locations = secondaries + ([primary] if primary in locations else [])
            ok = False
            for node_id in locations:
                node = self._node_table.get(node_id)
                if node is None or node.get("state") != "ALIVE":
                    await self._refresh_node_table()
                    node = self._node_table.get(node_id)
                    if node is None or node.get("state") != "ALIVE":
                        continue
                try:
                    await self._transfer_from_node(oid, node["address"])
                    ok = True
                    break
                except ObjectMissingOnHolder as e:
                    logger.warning("Holder %s no longer has %s: %s",
                                   node_id[:8], oid.hex()[:12], e)
                    # ONLY on holder-reported absence (evicted secondary):
                    # deregister so later pullers skip the stale entry.
                    # Generic transfer failures (e.g. THIS node's store is
                    # full) must not wipe live copies from the directory.
                    try:
                        await owner.call(
                            "RemoveObjectLocation",
                            {"id": oid, "node_id": node_id},
                            timeout=get_config().object_directory_rpc_timeout_s)
                    except Exception:
                        pass
                except Exception as e:
                    logger.warning("Transfer of %s from %s failed: %s",
                                   oid.hex()[:12], node_id[:8], e)
            if ok:
                try:
                    await owner.call(
                        "AddObjectLocation",
                        {"id": oid, "node_id": self.node_id.hex()},
                        timeout=get_config().object_directory_rpc_timeout_s)
                except Exception:
                    pass  # directory update is best-effort
            await owner.close()
            return ok
        finally:
            self._release_pull()
            done_fut = self._fetching.pop(oid, None)
            if done_fut is not None and not done_fut.done():
                done_fut.set_result(self.store.contains(oid) == 2)

    def _store_client(self, node_address: str) -> RpcClient:
        client = self._remote_store_clients.get(node_address)
        if client is None:
            client = RpcClient(node_address)
            self._remote_store_clients[node_address] = client
        return client

    async def _transfer_from_node(self, oid: bytes, node_address: str) -> None:
        """Preferred path: the holder pushes chunks at its own pace (one
        request, pipelined transfers); legacy per-chunk pull as fallback."""
        client = self._store_client(node_address)
        try:
            reply = await client.call(
                "PushObject", {"id": oid, "to": self.address}, timeout=30.0)
        except Exception:
            reply = {}
        if reply.get("pushing"):
            fut = self._fetching.get(oid)
            if fut is not None:
                # Resolved by the seal of the last pushed chunk. Bail on a
                # STALLED push quickly (holder died / failed silently) —
                # parking 120s here would pin an admission slot and starve
                # get-class pulls behind a few bad holders. The
                # no-progress grace also covers the window BEFORE the
                # first chunk (a busy holder may need seconds to start).
                started = time.monotonic()
                deadline = started + get_config().object_push_complete_timeout_s
                while time.monotonic() < deadline:
                    try:
                        await asyncio.wait_for(asyncio.shield(fut), 2.0)
                        break
                    except asyncio.TimeoutError:
                        state = self._receiving.get(oid)
                        last = state["last_progress"] if state else started
                        if (time.monotonic() - last
                                > get_config().object_push_stall_timeout_s):
                            break  # no chunk in the window: holder is gone
                if self.store.contains(oid) == 2:
                    return
                raise KeyError(f"push of {oid.hex()} did not complete")
        if not reply.get("found", True):
            raise ObjectMissingOnHolder(f"{oid.hex()} not on {node_address}")
        if self._receiving.pop(oid, None) is not None:
            # A failed partial push left an unsealed allocation; reclaim it
            # before the puller-driven fallback recreates the object.
            self.store.delete(oid, force=True)
        await self._fetch_from_node(oid, node_address)

    # --------------------------------------------------- push manager (holder)
    async def handle_PushObject(self, p: dict) -> dict:
        """A puller asks THIS node (a holder) to push ``id`` to it. Chunks
        go out holder-driven with a bounded in-flight window — no
        per-chunk round-trip stall (reference push_manager.h:30)."""
        oid = p["id"]
        info = self.store.get_info(oid)
        if info is None and oid in self._spilled:
            try:
                await self._restore_spilled(oid)
            except StoreFullError:
                return {"found": False}
            info = self.store.get_info(oid)
        if info is None:
            return {"found": False}
        self.transfer_stats["pushes_served"] += 1
        # Pin BEFORE the spawned task runs: between this handler returning
        # and _push_to starting, a spill triggered by another handler could
        # evict the object and leave _push_to reading a stale offset.
        self.store.add_ref(oid)
        spawn(self._push_to(oid, info, p["to"]))
        return {"found": True, "pushing": True}

    async def _push_to(self, oid: bytes, info: tuple, dest_address: str) -> None:
        """Stream chunks to ``dest``; the caller already holds a store ref
        (released here) so the pages can't move mid-push."""
        cfg = get_config()
        store_offset, data_size, meta_size = info
        total = data_size + meta_size
        try:
            client = self._store_client(dest_address)

            def _check(reply: dict) -> None:
                if not reply.get("ok"):
                    # Receiver is rejecting chunks (store full, create
                    # failed): abort the stream instead of shipping the
                    # rest of a multi-GB object into a void.
                    raise RuntimeError(
                        f"receiver rejected chunk: {reply.get('error')}")

            window: list = []
            pos = 0
            while pos < total:
                size = min(cfg.object_manager_chunk_size, total - pos)
                data = bytes(self.store.read(store_offset + pos, size))
                window.append(spawn(client.call("PushObjectChunk", {
                    "id": oid, "offset": pos, "data": data,
                    "data_size": data_size, "meta_size": meta_size,
                }, timeout=cfg.object_transfer_rpc_timeout_s)))
                self.transfer_stats["chunks_served"] += 1
                pos += size
                if len(window) >= cfg.push_manager_chunks_in_flight:
                    _check(await window.pop(0))
            for w in window:
                _check(await w)
        except Exception as e:
            logger.warning("push of %s to %s failed: %s",
                           oid.hex()[:12], dest_address, e)
        finally:
            self.store.release(oid)

    # ------------------------------------------------ push manager (receiver)
    async def handle_PushObjectChunk(self, p: dict) -> dict:
        oid = p["id"]
        if self.store.contains(oid) == 2 or oid in self._spilled:
            return {"ok": True}  # already have it (duplicate push)
        state = self._receiving.get(oid)
        if state is None:
            try:
                offset = self._create_with_spill(
                    oid, p["data_size"], p["meta_size"])
            except StoreFullError:
                return {"ok": False, "error": "store_full"}
            except Exception:
                return {"ok": False, "error": "create_failed"}
            state = self._receiving[oid] = {
                "offset": offset,
                "total": p["data_size"] + p["meta_size"],
                # Completion = UNIQUE offsets covering total: a retry push
                # (new holder after a dead one) re-sends offsets already
                # written — counting raw bytes would seal with holes.
                "chunks": {},
                "last_progress": time.monotonic(),
            }
            self._object_meta[oid] = {"size": state["total"]}
        self.store.write(state["offset"] + p["offset"], p["data"])
        state["chunks"][p["offset"]] = len(p["data"])
        state["last_progress"] = time.monotonic()
        if sum(state["chunks"].values()) >= state["total"]:
            self._receiving.pop(oid, None)
            self.store.seal(oid)
            self.store.release(oid)
            fut = self._fetching.get(oid)
            if fut is not None and not fut.done():
                fut.set_result(True)
        return {"ok": True}

    async def _fetch_from_node(self, oid: bytes, node_address: str) -> None:
        cfg = get_config()
        client = self._store_client(node_address)
        first = await client.call(
            "FetchObjectChunk", {"id": oid, "offset": 0, "size": cfg.object_manager_chunk_size},
            timeout=cfg.object_transfer_rpc_timeout_s,
        )
        if not first.get("found"):
            raise ObjectMissingOnHolder(f"{oid.hex()} not on {node_address}")
        data_size, meta_size = first["data_size"], first["meta_size"]
        total = data_size + meta_size
        offset = self._create_with_spill(oid, data_size, meta_size)
        self._object_meta[oid] = {"size": total}
        chunk = first["data"]
        self.store.write(offset, chunk)
        pos = len(chunk)
        while pos < total:
            r = await client.call(
                "FetchObjectChunk",
                {"id": oid, "offset": pos, "size": cfg.object_manager_chunk_size},
                timeout=cfg.object_transfer_rpc_timeout_s,
            )
            data = r["data"]
            self.store.write(offset + pos, data)
            pos += len(data)
        self.store.seal(oid)
        self.store.release(oid)

    async def handle_FetchObjectChunk(self, p: dict) -> dict:
        info = self.store.get_info(p["id"])
        if info is None and p["id"] in self._spilled:
            try:
                await self._restore_spilled(p["id"])
            except StoreFullError:
                return {"found": False}  # puller retries other replicas / later
            info = self.store.get_info(p["id"])
        if info is None:
            return {"found": False}
        store_offset, data_size, meta_size = info
        total = data_size + meta_size
        start = p["offset"]
        size = min(p["size"], total - start)
        data = bytes(self.store.read(store_offset + start, size))
        return {"found": True, "data": data, "data_size": data_size, "meta_size": meta_size}

    async def handle_PlasmaContains(self, p: dict) -> dict:
        return {"state": self.store.contains(p["id"])}

    async def handle_PlasmaAddRef(self, p: dict) -> dict:
        self.store.add_ref(p["id"])
        return {}

    async def handle_PlasmaRelease(self, p: dict) -> dict:
        reader = p.get("reader")
        if reader is None:
            self.store.release(p["id"])
            return {}
        # Reader-accounted release: only drop a ref this reader actually
        # holds, so duplicate sends (RPC retry) or releases arriving after
        # _on_worker_dead already reaped the reader can't drop refs owned
        # by other readers.
        refs = self._read_refs.get(reader)
        if refs is not None and refs.get(p["id"], 0) > 0:
            self.store.release(p["id"])
            left = refs[p["id"]] - 1
            if left > 0:
                refs[p["id"]] = left
            else:
                refs.pop(p["id"], None)
            if not refs:
                self._read_refs.pop(reader, None)
        return {}

    async def handle_PlasmaDelete(self, p: dict) -> dict:
        oid = p["id"]
        deleted = self.store.delete(oid, p.get("force", False))
        if deleted:
            self._pinned.pop(oid, None)
        elif self.store.contains(oid) and not p.get("force"):
            # Still read-referenced: deferred delete — unpin so the last
            # PlasmaRelease makes it LRU-evictable instead of leaking it.
            self.store.unpin(oid)
            self._pinned.pop(oid, None)
            deleted = True
        if self._spilled.pop(oid, None) is not None:
            self._spill_pending.pop(oid, None)
            try:
                os.unlink(self._spill_path(oid))
            except OSError:
                pass
            deleted = True
        if deleted:
            self._object_meta.pop(oid, None)
        return {"deleted": deleted}

    # --------------------------------------------------- placement-group 2PC
    async def handle_ReserveBundle(self, p: dict) -> dict:
        key = (p["pg_id"], p["bundle_index"])
        if key in self._pg_bundles:
            # Idempotent: a restarted GCS re-drives 2PC for PENDING groups;
            # double-acquiring here would leak the bundle's resources.
            return {"ok": True}
        request = ResourceSet(p["resources"])
        if not self.resources.can_fit(request):
            return {"ok": False}
        self.resources.acquire(request)
        self._pg_bundles[key] = {
            "resources": request,
            "used": ResourceSet(),
            "committed": False,
            "reserved_at": time.monotonic(),
        }
        return {"ok": True}

    async def handle_CommitBundle(self, p: dict) -> dict:
        b = self._pg_bundles.get((p["pg_id"], p["bundle_index"]))
        if b is not None:
            b["committed"] = True
        return {"ok": b is not None}

    def _drop_bundle(self, key: tuple) -> None:
        """Release one bundle reservation back to the node pool and admit
        parked leases (shared by 2PC cancel and heartbeat reconciliation).
        TPU shares still behind a device-release fence (a bundle-leased
        worker being killed, its process not yet confirmed dead) are
        WITHHELD here — the fence releases them straight to the node pool
        when the holder dies, so PG teardown can't re-grant a held chip."""
        b = self._pg_bundles.pop(key, None)
        if b is not None:
            res = b["resources"]
            fenced = self._fence_pending.get(key, 0.0)
            if fenced > 0:
                res = res.subtract(ResourceSet({"TPU": min(
                    fenced, res.get("TPU"))}), allow_negative=True)
            self.resources.release(res)
            self._wake_lease_waiters()

    async def handle_CancelBundle(self, p: dict) -> dict:
        self._drop_bundle((p["pg_id"], p["bundle_index"]))
        return {}

    async def handle_ReturnBundle(self, p: dict) -> dict:
        return await self.handle_CancelBundle(p)

    async def handle_ReleaseReader(self, p: dict) -> dict:
        """Drop ALL read refs held by a reader (clean shutdown path: a
        driver flushes its pins in one call instead of per-object releases
        racing its io-loop teardown)."""
        for oid, count in self._read_refs.pop(p.get("reader") or "", {}).items():
            for _ in range(count):
                self.store.release(oid)
        return {}

    # ----------------------------------------------------------------- debug
    async def handle_ListWorkers(self, p: dict) -> dict:
        return {
            "workers": [
                {"worker_id": w.worker_id, "state": w.state, "pid": w.pid,
                 "address": w.address, "actor_id": w.actor_id,
                 "lease": w.lease_resources.to_dict()}
                for w in self._workers.values()
            ]
        }

    async def handle_ListObjects(self, p: dict) -> dict:
        limit = p.get("limit", 1000)
        out = []
        total = len(self._object_meta)
        for oid, meta in list(self._object_meta.items())[:limit]:
            if oid in self._spilled:
                state_name = "SPILLED"
            else:
                state = self.store.contains(oid)
                state_name = {0: "ABSENT", 1: "CREATED", 2: "SEALED"}.get(state, "?")
            out.append({"object_id": oid.hex(), "size": meta["size"],
                        "state": state_name, "pinned": oid in self._pinned})
        # Truncation is reported, never silent: the state API warns when a
        # listing hit its limit.
        return {"objects": out, "total": total, "truncated": total > limit}

    async def handle_CaptureProfile(self, p: dict) -> dict:
        """Trigger an on-demand jax.profiler capture on one of this node's
        workers (cli profile --node ...). Prefers a busy (leased/dedicated)
        worker — the one actually touching the accelerator — then idle,
        then the driver. The finished artifact is registered with the GCS
        so it shows up under /api/profiles."""
        target_id = p.get("worker_id") or ""
        candidates = [w for w in self._workers.values()
                      if w.address and w.state not in ("dead", "starting")]
        if target_id:
            candidates = [w for w in candidates if w.worker_id == target_id]
        rank = {"dedicated": 0, "leased": 1, "idle": 2, "driver": 3}
        candidates.sort(key=lambda w: rank.get(w.state, 4))
        if not candidates:
            return {"error": "no reachable worker on node "
                             f"{self.node_id.hex()[:8]}"
                             + (f" matching worker_id {target_id}" if target_id else "")}
        target = candidates[0]
        duration = float(p.get("duration", 2.0))
        outdir = os.path.join(self._session_dir, "profiles")
        client = RpcClient(target.address)
        try:
            reply = await client.call(
                "CaptureProfile",
                {"duration": duration, "output_dir": outdir},
                timeout=duration + 120.0)
        except Exception as e:
            return {"error": f"worker {target.worker_id[:12]} capture failed: {e}"}
        finally:
            await client.close()
        if reply.get("path"):
            profile = {
                "path": reply["path"],
                "node_id": self.node_id.hex(),
                "worker_id": target.worker_id,
                "worker_state": target.state,
                "duration": reply.get("duration", duration),
            }
            try:
                await self._gcs.call("RegisterProfile", {"profile": profile},
                                     timeout=5.0)
            except Exception:
                pass
            reply.setdefault("node_id", self.node_id.hex())
        return reply

    async def handle_DebugState(self, p: dict) -> dict:
        return {
            "node_id": self.node_id.hex(),
            "resources": self.resources.to_dict(),
            "num_workers": len(self._workers),
            "idle": len(self._idle),
            "store_used": self.store.used(),
            "store_objects": self.store.num_objects(),
            "spilled_objects": len(self._spilled),
            "spilled_bytes_total": self._spilled_bytes_total,
            "restored_bytes_total": self._restored_bytes_total,
        }

    # ----------------------------------------------------------- diagnostics
    def _debug_state_snapshot(self) -> dict:
        """Full raylet internals for debug_state.txt / GetDebugState /
        wedge reports: the lease admission queue with per-entry ages (the
        round-5 cascade was invisible precisely because this view did not
        exist), worker-pool states, bundle ledger, store/spill/OOM
        counters (reference node_manager.cc DebugString)."""
        now = time.monotonic()
        qnow = chaos_clock.now()
        lease_queue = [
            {
                "shape": e["request"].to_dict(),
                "priority": e["prio"],
                "seq": e["seq"],
                "age_s": round(qnow - e.get("enqueued_at", qnow), 3),
                "granted": e["fut"].done(),
            }
            for e in self._admission_queue
        ]
        workers_by_state: dict[str, int] = {}
        for w in self._workers.values():
            workers_by_state[w.state] = workers_by_state.get(w.state, 0) + 1
        return {
            "node_id": self.node_id.hex(),
            "address": self.address,
            "uptime_s": round(now - self._started_at, 1),
            "resources": self.resources.to_dict(),
            "lease_queue_depth": len(self._admission_queue),
            "lease_queue": lease_queue,
            "lease_waiters": len(self._lease_waiters),
            "pending_demand": [
                {"shape": dict(shape), "count": count}
                for shape, count in self._pending_lease_demand.items()
            ],
            "workers_by_state": workers_by_state,
            "num_workers": len(self._workers),
            "idle_workers": len(self._idle),
            "pg_bundles": [
                {"pg_id": key[0], "bundle_index": key[1],
                 "committed": b.get("committed", False),
                 "resources": b["resources"].to_dict(),
                 "used": b["used"].to_dict()}
                for key, b in self._pg_bundles.items()
            ],
            "fence_pending": {str(k): v for k, v in self._fence_pending.items()},
            "store": {
                **self._store_stats(),
                "receiving": len(self._receiving),
                "pull_inflight": self._pull_inflight,
                "pull_waiters": len(self._pull_waiters),
            },
            "hbm": _hbm_snapshot(),
            "worker_rss_bytes": {
                wid[:12]: rss for wid, rss in self._worker_rss().items()},
            "transfer_stats": dict(self.transfer_stats),
            "worker_spawns": dict(self._spawn_stats),
            "zygote_pool": {
                (key or "default"): dict(zip(("idle", "starting"),
                                             self._pool_counts(key)))
                for key in ["", *self._pool_keys]
            },
            "zygote_keys": [k for k in self._pool_keys],
            "draining": self._draining,
            "drain_reason": self._drain_reason,
            "oom_kills_total": self._oom_kills_total,
            "wedge_events_total": self._wedge_events_total,
            "orphan_leases_total": self._orphan_leases_total,
            "loop_pinned_workers": sum(
                1 for w in self._workers.values() if w.loop_pinned),
        }

    async def handle_GetDebugState(self, p: dict) -> dict:
        return {"debug_state": self._debug_state_snapshot()}

    async def _publish_error_event(self, event: dict) -> None:
        """Best-effort ErrorEvent publish to the GCS error-info channel."""
        try:
            await self._gcs.call("PublishError", {"event": event}, timeout=5.0)
        except Exception:
            pass

    async def _debug_dump_loop(self) -> None:
        """Write ``debug_state_<node>.txt`` into the session dir on an
        interval (reference: raylet debug_state.txt dumps). Polls the
        config each tick so tests (and live operators) can retune the
        cadence without restarting the raylet."""
        from ..diagnostics.debug_state import write_debug_state

        last = 0.0
        while True:
            await asyncio.sleep(0.5)
            interval = get_config().debug_state_dump_interval_s
            now = time.monotonic()
            if interval <= 0 or now - last < interval:
                continue
            last = now
            try:
                path = os.path.join(
                    self._session_dir,
                    f"debug_state_{self.node_id.hex()[:12]}.txt")
                snapshot = self._debug_state_snapshot()
                await asyncio.get_running_loop().run_in_executor(
                    None, write_debug_state, path, "raylet", snapshot)
            except Exception:
                logger.exception("debug-state dump failed")

    async def _lease_watchdog_loop(self) -> None:
        """Lease-wedge watchdog: a queued admission entry older than the
        threshold whose request WOULD fit the free pool means the queue is
        wedged — head-of-line blocked behind an unsatisfiable entry, or a
        missed wake. Fire an ErrorEvent carrying the full queue snapshot
        (the exact instrumentation the round-5 mid-suite lease-timeout
        cascade lacked), then nudge the dispatcher as a self-heal."""
        from ..diagnostics.errors import make_event

        while True:
            cfg = get_config()
            await chaos_clock.sleep(max(0.1, cfg.lease_wedge_check_interval_s))
            try:
                await self._scan_orphan_leases(cfg)
            except Exception:
                logger.exception("orphan-lease scan failed")
            threshold = cfg.lease_wedge_threshold_s
            if threshold <= 0 or not self._admission_queue:
                continue
            try:
                now = chaos_clock.now()
                fired = False
                for entry in list(self._admission_queue):
                    age = now - entry.get("enqueued_at", now)
                    if (age < threshold or entry.get("wedge_reported")
                            or entry["fut"].done()):
                        continue
                    if not self.resources.can_fit(entry["request"]):
                        continue  # genuinely waiting for capacity: not a wedge
                    entry["wedge_reported"] = True
                    self._wedge_events_total += 1
                    fired = True
                    shape = entry["request"].to_dict()
                    logger.error(
                        "lease-wedge watchdog: lease %s (prio %d) pending %.1fs "
                        "while matching resources are free; queue depth %d",
                        shape, entry["prio"], age, len(self._admission_queue))
                    spawn(self._publish_error_event(make_event(
                        "lease_wedge",
                        f"lease {shape} pending {age:.1f}s on node "
                        f"{self.node_id.hex()[:8]} while matching resources are "
                        f"free (queue depth {len(self._admission_queue)})",
                        source="raylet", node_id=self.node_id.hex(),
                        extra={"debug_state": self._debug_state_snapshot()})))
                if fired:
                    # Self-heal a missed wake; a truly blocked head keeps the
                    # queue intact and the report stands.
                    self._dispatch_admission()
            except Exception:
                # The watchdog must outlive any one bad scan (e.g. the
                # store closing mid-snapshot during teardown).
                logger.exception("lease-wedge watchdog scan failed")

    async def _scan_orphan_leases(self, cfg) -> None:
        """Reclaim granted leases whose owner never acknowledged them.

        The grant reply can be lost in transit (chaos, or a real network
        fault): the owner times out and retries elsewhere while this
        raylet keeps the reservation and the leased worker forever. That
        strand was the root cause of the ROADMAP-1c mid-suite
        lease-timeout cascade — each lost reply shrank the node's usable
        CPU pool until every later lease timed out. Before reclaiming,
        the worker itself is probed: a worker that is executing (or whose
        push count moves between two probes) proves the owner DID receive
        the grant — only its AckLease was lost — and the lease is kept.
        """
        timeout = cfg.lease_orphan_timeout_s
        if timeout <= 0:
            return
        now = chaos_clock.now()
        for w in list(self._workers.values()):
            if w.state not in ("leased", "dedicated") or w.lease_acked:
                continue
            if w.loop_pinned:
                # The owner declared a parked compiled-loop executor on
                # this worker: it legitimately never finishes, never
                # pushes, and may be unprobeable mid-chaos — reclaiming
                # it would kill a live pipeline. Unpinned at teardown.
                continue
            if not w.lease_granted_at or now - w.lease_granted_at < timeout:
                continue
            probe = None
            if w.address:
                try:
                    client = RpcClient(w.address)
                    probe = await client.call("LeaseProbe", {}, timeout=5.0)
                    await client.close()
                except Exception:
                    probe = None  # unreachable/dead: reclaim below
            if probe is not None:
                if probe.get("executing"):
                    w.lease_acked = True  # grant reached the owner after all
                    continue
                if w.orphan_probe is None:
                    # First look: sample the push counter; confirm on the
                    # next scan so a push in flight right now isn't raced.
                    w.orphan_probe = probe.get("pushes_total", 0)
                    continue
                if probe.get("pushes_total", 0) != w.orphan_probe:
                    w.lease_acked = True
                    continue
            self._reclaim_orphan_lease(w, now - w.lease_granted_at, cfg)

    def _reclaim_orphan_lease(self, w: WorkerHandle, age: float, cfg) -> None:
        from ..diagnostics.errors import make_event

        self._orphan_leases_total += 1
        logger.error(
            "orphan-lease reclaim: worker %s lease un-acked for %.1fs (grant "
            "reply lost?); releasing %s",
            w.worker_id[:12], age, w.lease_resources.to_dict())
        # Classification must be robust to stale queue state (a previous
        # workload's un-acked strands aging out mid-scan, the cross-file
        # watchdog flake): the "blocked behind an orphaned lease" wedge
        # is claimed ONLY for a live head entry that could not fit the
        # free pool before this reclaim but CAN after it — the orphan
        # provably held its resources. A head that already fits is the
        # canonical missed-wake wedge and belongs to the watchdog loop's
        # own scan (whose report names the free resources); an
        # unsatisfiable head is infeasible, not orphan-blocked.
        head = next((e for e in self._admission_queue
                     if not e["fut"].done()), None)
        head_fits_before = (head is not None
                            and self.resources.can_fit(head["request"]))
        spawn(self._publish_error_event(make_event(
            "lease_orphan",
            f"reclaimed un-acked lease on worker {w.worker_id[:12]} after "
            f"{age:.1f}s — the grant reply likely never reached the owner",
            source="raylet", node_id=self.node_id.hex(),
            worker_id=w.worker_id, actor_id=w.actor_id)))
        if self._release_lease(w):
            self._on_worker_dead(w)  # TPU device fence: worker being killed
        else:
            w.state = "idle"
            w.actor_id = ""
            w.lease_acked = True
            w.orphan_probe = None
            w.last_idle_time = time.monotonic()
            self._idle.append(w.worker_id)
        if head is not None and cfg.lease_wedge_threshold_s > 0:
            head_age = chaos_clock.now() - head.get("enqueued_at", 0.0)
            if (head_age >= cfg.lease_wedge_threshold_s
                    and not head.get("wedge_reported")
                    and not head_fits_before
                    and self.resources.can_fit(head["request"])):
                head["wedge_reported"] = True
                self._wedge_events_total += 1
                spawn(self._publish_error_event(make_event(
                    "lease_wedge",
                    f"lease {head['request'].to_dict()} pending "
                    f"{head_age:.1f}s on node {self.node_id.hex()[:8]} "
                    f"blocked behind an orphaned lease grant (worker "
                    f"{w.worker_id[:12]}, queue depth "
                    f"{len(self._admission_queue)})",
                    source="raylet", node_id=self.node_id.hex(),
                    extra={"debug_state": self._debug_state_snapshot()})))
        self._wake_lease_waiters()


def _hbm_snapshot() -> dict:
    from ..observability.memory import hbm_stats

    return hbm_stats()


def _node_memory_usage_fraction() -> float:
    """Fraction of node memory in use, from /proc/meminfo (reference
    memory_monitor.cc GetLinuxMemoryBytes; cgroup limits not consulted)."""
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                name, _, rest = line.partition(":")
                fields[name] = int(rest.split()[0])  # kB
        total = fields.get("MemTotal", 0)
        avail = fields.get("MemAvailable", total)
        if total <= 0:
            return 0.0
        return 1.0 - avail / total
    except OSError:
        return 0.0


def _in_loop() -> bool:
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False
