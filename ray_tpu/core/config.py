"""Typed global config table with environment overrides.

Equivalent of the reference's ``RAY_CONFIG`` macro table
(``src/ray/common/ray_config_def.h``, 223 entries): every knob is a typed
entry, overridable via a ``RAY_TPU_<name>`` environment variable or an
explicit dict (the reference passes a JSON blob as ``--raylet_config``).
Only knobs the TPU build actually consumes are defined; add entries here as
subsystems grow.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, fields
from typing import Any

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class RayTpuConfig:
    # --- object store / plasma ---------------------------------------------
    # Max bytes serialized inline into the owner's in-process memory store
    # instead of plasma (reference: ``max_direct_call_object_size``).
    max_inline_object_size: int = 100 * 1024
    # Default plasma capacity as a fraction of system memory.
    object_store_memory_fraction: float = 0.3
    object_store_minimum_memory_bytes: int = 64 * 1024 * 1024
    # Chunk size for inter-node object transfer (reference: 5 MiB chunks,
    # ``object_manager.h:117``).
    object_manager_chunk_size: int = 5 * 1024 * 1024
    # Proactive spill starts when store usage exceeds this fraction
    # (reference ``object_spilling_threshold``).
    object_spilling_threshold: float = 0.8
    # Node memory watcher (reference ``src/ray/common/memory_monitor.h:52``):
    # above this fraction of node memory the newest retriable lease is killed.
    memory_usage_threshold: float = 0.95
    # 0 disables the watcher.
    memory_monitor_refresh_ms: int = 250
    # Stream worker stdout/stderr lines to the driver via the GCS log
    # channel (reference ``log_monitor.py`` + worker log redirection).
    log_to_driver: bool = True
    log_monitor_poll_ms: int = 500

    # --- scheduling ----------------------------------------------------------
    # Hybrid policy: pack onto nodes below this utilization score, then spread
    # (reference ``hybrid_scheduling_policy.cc``).
    scheduler_spread_threshold: float = 0.5
    # Max tasks dispatched to one worker lease before returning it.
    worker_lease_timeout_ms: int = 500
    max_pending_lease_requests_per_scheduling_category: int = 10
    # Keep a drained lease warm briefly before returning the worker: a
    # sync submit->get->submit loop re-pushes on the SAME lease instead of
    # paying acquire+return RPCs per task (reference: worker lease reuse).
    lease_idle_grace_ms: int = 20
    # How long a pipeline parks on a sibling's in-flight coalesced lease
    # RPC before de-coalescing and issuing its own (the stuck-leader
    # degrade: a leader wedged on a dropped reply or slow spawn must not
    # hold every pipeline hostage for its full RPC timeout). Read through
    # the chaos clock, so VirtualClock replays degrade deterministically.
    lease_coalesce_degrade_ms: float = 500.0

    # --- worker pool ---------------------------------------------------------
    num_prestart_workers: int = 2
    worker_register_timeout_s: float = 30.0
    # Idle pool shrink: a worker idle this long while its env key's pool
    # is over target is reaped (re-spawning later is a ~ms zygote fork).
    # Generous default: sub-second reaping made burst-heavy suites churn
    # kill/re-fork between back-to-back workloads. 0 disables shrink.
    idle_worker_killing_time_threshold_ms: int = 2500
    maximum_startup_concurrency: int = 4
    # Max normal-task specs pushed to a leased worker in ONE RPC: the
    # batch-submit path is RPC/handoff-bound, not execution-bound.
    task_push_batch_size: int = 16
    # Max workers ONE RequestWorkerLease may grant (owner-side lease
    # multiplexing): a deep task queue asks for several workers in one
    # round trip, and same-shape lease requests across pipelines coalesce
    # onto the in-flight RPC instead of each paying its own. Extra grants
    # are best-effort — the raylet only adds workers that are idle and
    # admissible right now. 1 = the legacy one-lease-per-RPC protocol.
    lease_grant_batch_size: int = 4
    # Fork workers from a warm pre-imported zygote process instead of
    # paying interpreter boot + imports per worker. Zygotes are
    # runtime-env-KEYED: the first worker of an env (env_vars /
    # working_dir / py_modules / pip) boots a zygote with that env baked
    # into its image, and every later worker of the same env hash forks
    # from it in milliseconds. Interpreter-level envs (conda /
    # py_executable / container / image_uri) can never fork — those
    # always cold-spawn (the PR 1 enforcement path).
    enable_worker_zygote: bool = True
    # Pre-forked idle workers kept warm PER runtime-env key (the zygote
    # pool): an actor-creation lease binds a pooled registered process
    # instead of paying fork+register inline. The default env's target is
    # max(num_prestart_workers, zygote_pool_size). 0 disables keyed
    # pooling (default-env prestart still applies).
    zygote_pool_size: int = 2
    # Max pool spawns kicked per maintenance tick per env key (refill
    # rate bound — a drained pool refills over a few ticks instead of
    # fork-storming the node).
    zygote_pool_refill_batch: int = 2
    # Distinct non-default runtime-env keys kept warm at once. Over the
    # cap the least-recently-leased key is evicted: its zygote dies and
    # its idle pooled workers are killed (env-mismatch eviction).
    zygote_pool_max_keys: int = 4
    # Concurrent in-flight spawns allowed when the env's zygote is LIVE
    # (forks are ~ms and pay no import cost — the lower
    # maximum_startup_concurrency bound exists to protect cold spawns'
    # interpreter-boot storms, and throttling a 1k-actor creation storm
    # to 4 concurrent ms-scale forks was pure queueing delay).
    zygote_max_fork_concurrency: int = 16
    # Ray Client sessions: the client pings every interval; the proxy
    # reaps sessions silent for the timeout (kills session-owned actors,
    # drops refs/streams, finishes the client job) — crash cleanup for
    # drivers that never call disconnect (ref: ray client reconnect grace).
    client_ping_interval_s: float = 5.0
    client_session_timeout_s: float = 30.0
    # Object-manager push: chunks a holder keeps in flight toward one
    # receiver (reference push_manager.h:30 sender-side flow control).
    push_manager_chunks_in_flight: int = 8
    # Pull admission: concurrent inbound object transfers per raylet;
    # excess pulls queue by class get > wait > task-arg
    # (reference pull_manager.h:51 prioritized bundles).
    pull_manager_max_concurrent: int = 4
    # Receiver-side push watchdog: abandon an in-flight inbound push when
    # no chunk lands for this long (holder died mid-stream), and cap the
    # total wall time one push may take before falling back to a pull.
    object_push_stall_timeout_s: float = 10.0
    object_push_complete_timeout_s: float = 120.0
    # GC grace for unsealed partial-receive allocations with no progress
    # (unsealed objects are neither spillable nor evictable).
    object_receive_gc_grace_s: float = 60.0
    # Per-chunk transfer RPC timeout (push and pull chunk calls).
    object_transfer_rpc_timeout_s: float = 60.0
    # Owner/object-directory control RPCs (GetObjectLocations, location
    # add/remove) — small messages, but cross-node.
    object_directory_rpc_timeout_s: float = 10.0
    # Device-release fence: how long to wait for a TPU-holding worker
    # process to exit (after SIGTERM, then SIGKILL) before re-granting the
    # TPU resource anyway. The libtpu device lock is exclusive per process
    # and only the kernel releases it, on process death.
    tpu_release_fence_timeout_s: float = 30.0
    # Grant-side fence: how long the node's FIRST outstanding TPU lease
    # waits for the host's libtpu device lock to be free (the holder may
    # be a process the raylet never tracked — a benchmark phase, a stray
    # trainer). Longer than the release fence: an external holder's
    # teardown (checkpoint flush, host transfer drain) is invisible, so
    # give it real time before granting into a crash-loop.
    tpu_grant_fence_timeout_s: float = 90.0

    # --- fault tolerance -----------------------------------------------------
    # Preemption drain window: seconds between a node's preemption notice
    # (GCE-style, or an injected `preempt_slice` chaos rule) and the VM
    # reclaim — the raylet drains (no new leases, task events flushed)
    # and then its workers are killed. GCE gives spot TPU VMs ~30 s;
    # tests/benches shrink it. Read through the chaos clock, so a
    # VirtualClock replays the window in milliseconds.
    preempt_grace_s: float = 10.0
    # GCE metadata-server preemption watcher: when enabled, every raylet
    # polls the instance metadata `preempted` key (flips to TRUE ~30 s
    # before a spot VM reclaim) and feeds the existing PreemptionNotice
    # drain path the moment it fires — no RPC from the control plane
    # needed. Off by default: only GCE instances have a metadata server.
    preempt_metadata_watch: bool = False
    preempt_metadata_url: str = ("http://metadata.google.internal/"
                                 "computeMetadata/v1/instance/preempted")
    preempt_metadata_poll_s: float = 1.0
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    health_check_period_ms: int = 1000
    health_check_failure_threshold: int = 5
    lineage_max_bytes: int = 1 << 30

    # --- RPC -----------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_retry_base_delay_ms: int = 100
    rpc_retry_max_delay_ms: int = 5000
    rpc_max_retries: int = 5
    # Full-jitter exponential backoff (AWS style: sleep ~ U(0, base*2^n)).
    # Bare doubling synchronizes retry storms when many clients fail at
    # once (mass failure under chaos / a GCS blackout); jitter decorrelates
    # them. Off = the legacy deterministic delay*2 schedule.
    rpc_retry_jitter: bool = True
    # Fault-injection spec, format "Service.Method=req_prob,resp_prob"
    # (reference ``rpc_chaos.cc:34``, env RAY_testing_rpc_failure).
    # Extended clauses (chaos subsystem): "Method=nth:3,delay:50" fails
    # every 3rd call deterministically and delays the rest by 50 ms.
    testing_rpc_failure: str = ""
    # Seed for the probabilistic chaos modes (env-spec and FaultPlans).
    testing_rpc_failure_seed: int = 0xC0FFEE

    # --- chaos ---------------------------------------------------------------
    # Process clock for timeout-driven control loops (chaos/clock.py):
    # "" | "wall" | "virtual" | "virtual:RATE". Workers inherit the env
    # override, so RAY_TPU_chaos_clock=virtual:50 puts the whole cluster
    # on accelerated virtual time.
    chaos_clock: str = ""
    # Reclaim a granted-but-never-acknowledged worker lease after this
    # long (the owner acks right after the grant reply arrives; a grant
    # whose reply was lost strands the reservation forever otherwise —
    # the ROADMAP-1c lease-timeout cascade). 0 disables reclaim.
    lease_orphan_timeout_s: float = 10.0

    # --- GCS -----------------------------------------------------------------
    gcs_pubsub_poll_timeout_s: float = 30.0
    gcs_storage_backend: str = "memory"  # "memory" | "file"
    # Store shards for the GCS control-plane tables (task events, KV,
    # actor records — the reference's store_client/ split): one lock per
    # shard so N raylets' concurrent flushes ingest in parallel instead
    # of convoying; reads stay linearizable per key. 1 = legacy single
    # lock.
    gcs_store_shards: int = 8
    # Pub/sub fan-out batching: publishes within this window share ONE
    # subscriber wake-up instead of each notifying every long-poller (1k
    # actors churning used to mean 1k wakes × N subscribers per flush).
    # 0 = notify per publish (legacy).
    gcs_pubsub_batch_window_ms: float = 2.0
    # Max messages one long-poll reply carries per channel; a backlogged
    # subscriber drains the rest on its next poll (bounds reply size and
    # serialization time under churn storms).
    gcs_pubsub_max_batch_msgs: int = 1000

    # --- task events / observability ----------------------------------------
    task_events_buffer_size: int = 10000
    task_events_flush_interval_ms: int = 1000
    # Coalesce one task's status transitions recorded within this window
    # into ONE wire event per flush (SUBMITTED/LEASED/FINISHED become a
    # single dict with a `transitions` list; the GCS replays them in
    # order, so records and lease-stage histograms are identical to the
    # unbatched path). 0 = one wire event per transition (legacy).
    task_event_coalesce_ms: int = 1000
    enable_timeline: bool = True
    # Distributed tracing: trace-context propagation through TaskSpec /
    # serve requests + span recording (observability/tracing.py).
    enable_tracing: bool = True
    # Spans retained by the GCS span store (whole traces are evicted
    # oldest-first past this cap).
    span_events_buffer_size: int = 20000

    # --- diagnostics ---------------------------------------------------------
    # Retained ErrorEvents in the GCS error-info buffer (list_errors()).
    error_info_buffer_size: int = 1000
    # Raylet/GCS debug_state_*.txt dump cadence; 0 disables periodic dumps
    # (the GetDebugState RPC always works).
    debug_state_dump_interval_s: float = 10.0
    # Lease-wedge watchdog: fire an ErrorEvent when an admission-queue
    # entry has waited this long while its resources could be granted
    # (head-of-line blocking / missed wake). 0 disables the watchdog.
    lease_wedge_threshold_s: float = 10.0
    lease_wedge_check_interval_s: float = 1.0

    # --- memory observability ------------------------------------------------
    # Record a Python creation callsite on every user-facing ObjectRef
    # (reference record_ref_creation_sites; powers `cli memory` attribution).
    record_ref_creation_sites: bool = True
    # Cadence of per-worker memory summaries on the task-event flush path.
    memory_report_interval_ms: int = 2000
    # Rows per worker summary (totals stay exact; only the table is capped).
    memory_summary_max_entries: int = 200
    # GCS leak watcher: flag a worker/raylet whose refcount table or pinned
    # bytes grew monotonically across this many consecutive reports by at
    # least the byte/ref thresholds. 0 intervals disables the watcher.
    memory_leak_check_interval_s: float = 5.0
    memory_leak_intervals: int = 4
    memory_leak_min_growth_bytes: int = 1 << 20
    memory_leak_min_growth_refs: int = 50
    # On-demand jax.profiler capture (cli profile): hard cap per request.
    profile_max_duration_s: float = 60.0

    # --- workers / executor --------------------------------------------------
    # Thread pool depth per worker (long-poll actor methods park threads).
    worker_executor_threads: int = 64
    # Owner-side temporary hold on returned nested refs until the caller
    # registers as a borrower (reference: borrowed-ref grace).
    borrow_hold_ttl_s: float = 600.0
    borrow_sweep_interval_s: float = 30.0
    # Client-side actor address resolution deadline (PENDING/RESTARTING).
    actor_resolve_timeout_s: float = 120.0

    # --- streaming generators ------------------------------------------------
    generator_report_timeout_s: float = 30.0
    generator_wait_consumed_poll_s: float = 10.0

    # --- global GC -----------------------------------------------------------
    # Min seconds between cluster-wide gc.collect broadcasts.
    global_gc_interval_s: float = 5.0

    # --- compiled graphs -----------------------------------------------------
    dag_ready_timeout_s: float = 120.0
    dag_channel_capacity: int = 1 << 20
    # Compiled LOOPS (dag/loop.py): ring depth = max iterations in flight
    # before put() backpressures, and the dag.loop.tick span sampling
    # stride (0 disables tick spans; every tick still counts in the
    # ray_tpu_dag_loop_ticks_total metric).
    dag_loop_credits: int = 8
    dag_loop_span_every: int = 64
    # Tick stall attribution (observability/loop_recorder.py): each
    # resident stage records its per-tick wait_up/compute/wait_down split
    # into a fixed-size in-process ring and flushes aggregate histograms
    # on the span cadence above. Always-on by default — the dag bench's
    # loop_obs_overhead_frac cell guards the cost at ≤ 2% of tick
    # dispatch; False is the bench's recorder-off baseline.
    dag_loop_stall_recording: bool = True
    dag_loop_stall_ring: int = 256

    # --- serve ---------------------------------------------------------------
    serve_router_assign_timeout_s: float = 60.0
    serve_stream_item_timeout_s: float = 120.0
    serve_stream_backpressure_items: int = 256
    # Prefix/session affinity routing: requests carrying a prefix-group
    # key (explicit session id, or a hash of the prompt's leading
    # serve_prefix_group_chars characters ≈ the first token blocks under
    # the byte tokenizer) stick to the replica whose engine already holds
    # their KV — unless that replica is serve_affinity_spill_margin
    # in-flight requests hotter than the coolest candidate (load-aware
    # spill: never queue-blow a hot replica just for affinity). The
    # group→replica map is bounded LRU (serve_affinity_map_size).
    serve_affinity_map_size: int = 2048
    serve_affinity_spill_margin: int = 4
    serve_prefix_group_chars: int = 256
    # KV-page migration (disaggregated serving + spill migration): when a
    # prefix-group request spills off its affine replica, the spill
    # target pulls the group's hot KV pages from the previous replica
    # instead of cold-prefilling (serve_spill_migration). Streamed
    # migrations move kv_migration_chunk_pages pages per message over a
    # credit-based TCP loop channel; an importer that cannot finish
    # within kv_migration_timeout_s registers the contiguous prefix it
    # received and cold-prefills the rest.
    serve_spill_migration: bool = True
    kv_migration_chunk_pages: int = 8
    kv_migration_timeout_s: float = 60.0
    # --- overload protection (graceful degradation under load spikes) ---
    # Default end-to-end request deadline stamped at proxy ingress when the
    # client sends neither an `x-raytpu-deadline-ms` header nor a
    # `timeout_s` body field. A request that expires while still QUEUED
    # (router wait or engine admission queue) fails fast without touching
    # the engine; one that expires mid-decode has its slot aborted and its
    # pages freed the same tick. 0 disables the default (requests without
    # an explicit deadline never expire).
    serve_default_deadline_s: float = 0.0
    # Router-level queue bound: max requests allowed to WAIT for a replica
    # slot per (process, deployment) router when every replica is at its
    # max_ongoing cap. Over the bound the request is shed with a 503 +
    # Retry-After derived from the observed per-replica service rate
    # (reference Serve's max_queued_requests ingress backpressure).
    # 0 = unbounded (legacy blocking behavior).
    serve_max_queued_requests: int = 64
    # Shed policy over the bound: "cost" prefers shedding the request with
    # the largest cold suffix — a request whose prefix group maps to a
    # live replica is cheap (its KV is cached) and may preempt an
    # expensive (cold) waiter's queue slot; "fifo" always sheds the
    # incoming request.
    serve_shed_policy: str = "cost"
    # Replica circuit breaker: a replica that times out this many
    # CONSECUTIVE handles is marked open in the router and excluded from
    # routing; after the cooldown one half-open probe request is allowed
    # through — success closes the circuit, failure re-opens it.
    # 0 disables the breaker.
    serve_circuit_breaker_failures: int = 3
    serve_circuit_breaker_cooldown_s: float = 5.0
    # Extra free-page headroom the engine keeps when admitting new slots
    # (on top of the worst-case per-request reservation admission already
    # takes): admission refuses — and counts `admission_rejects`, leaving
    # the request in the queue — while free pages are below the reserve,
    # so in-flight KV migrations/imports never race running slots.
    serve_admission_watermark_pages: int = 0

    # --- data ----------------------------------------------------------------
    data_max_in_flight_tasks: int = 8
    data_per_op_concurrency: int = 4
    data_exchange_partitions: int = 8

    # --- TPU -----------------------------------------------------------------
    # Resource name prefix for slice-head scheduling (reference
    # ``_private/accelerators/tpu.py:70-192`` auto-creates TPU-{type}-head).
    tpu_head_resource_prefix: str = "TPU-"
    tpu_chips_per_host_default: int = 4

    def apply_env_overrides(self) -> None:
        for f in fields(self):
            env_key = _ENV_PREFIX + f.name
            if env_key in os.environ:
                raw = os.environ[env_key]
                setattr(self, f.name, _coerce(raw, f.type))

    def apply_dict(self, overrides: dict[str, Any]) -> None:
        valid = {f.name for f in fields(self)}
        for key, value in overrides.items():
            if key not in valid:
                raise ValueError(f"Unknown config key: {key}")
            setattr(self, key, value)

    def to_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @classmethod
    def from_json(cls, blob: str) -> "RayTpuConfig":
        cfg = cls()
        cfg.apply_dict(json.loads(blob))
        return cfg


def _coerce(raw: str, type_name: Any) -> Any:
    name = type_name if isinstance(type_name, str) else getattr(type_name, "__name__", str(type_name))
    if name == "bool":
        return raw.lower() in ("1", "true", "yes")
    if name == "int":
        return int(raw)
    if name == "float":
        return float(raw)
    return raw


_config_lock = threading.Lock()
_config: RayTpuConfig | None = None


def get_config() -> RayTpuConfig:
    global _config
    with _config_lock:
        if _config is None:
            _config = RayTpuConfig()
            _config.apply_env_overrides()
        return _config


def reset_config() -> None:
    global _config
    with _config_lock:
        _config = None
