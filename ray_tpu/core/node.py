"""Node bootstrap: start/stop the head services and the driver CoreWorker.

Equivalent of the reference's ``python/ray/_private/node.py``
(``start_head_processes``:1401) and ``services.py``. Difference from the
reference: the GCS and the raylet run as asyncio services on a dedicated
thread inside the driver process rather than as separate C++ processes —
worker processes are real subprocesses either way, and the
``cluster.Cluster`` harness can start additional raylets to get full
multi-node semantics on one machine (reference ``cluster_utils.py:135``).
"""

from __future__ import annotations

import os
import tempfile
import time

from .config import get_config
from .gcs import GcsServer
from .ids import JobID
from .raylet import Raylet
from .rpc import EventLoopThread
from .worker import MODE_DRIVER, CoreWorker, set_global_worker


class Node:
    def __init__(
        self,
        *,
        head: bool = True,
        gcs_address: str | None = None,
        num_cpus: float | None = None,
        resources: dict | None = None,
        labels: dict | None = None,
        object_store_memory: int | None = None,
        session_dir: str | None = None,
    ):
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="raytpu-session-")
        self.services_loop = EventLoopThread("raytpu-services")
        self.gcs: GcsServer | None = None
        if head:
            from .gcs_storage import storage_from_config

            self.gcs = GcsServer(storage=storage_from_config(self.session_dir),
                                 session_dir=self.session_dir)
            self.services_loop.run_sync(self.gcs.start())
            gcs_address = self.gcs.address
        assert gcs_address is not None
        self.gcs_address = gcs_address
        self.raylet = Raylet(
            gcs_address,
            num_cpus=num_cpus,
            resources=resources,
            labels=labels,
            object_store_capacity=object_store_memory,
            session_dir=self.session_dir,
        )
        self.services_loop.run_sync(self.raylet.start())

    def connect_driver(self, job_id: int = 1) -> CoreWorker:
        worker = CoreWorker(
            mode=MODE_DRIVER,
            gcs_address=self.gcs_address,
            raylet_address=self.raylet.address,
            node_id=self.raylet.node_id.hex(),
            store_path=self.raylet.store_path,
            store_capacity=self.raylet.object_store_capacity,
            job_id=JobID.from_int(job_id),
        )
        worker.connect()
        worker._gcs_call("AddJob", {"driver_address": worker.address})
        set_global_worker(worker)
        return worker

    def shutdown(self) -> None:
        try:
            self.services_loop.run_sync(self.raylet.stop(), timeout=30)
        except Exception:
            pass
        if self.gcs is not None:
            try:
                self.services_loop.run_sync(self.gcs.stop(), timeout=5)
            except Exception:
                pass
        self.services_loop.stop()
        _reap_worker_children(self.raylet)


def _reap_worker_children(raylet, deadline_s: float = 10.0) -> None:
    """Last-ditch sweep after raylet.stop: kill any worker of THIS NODE
    that survived stop() — e.g. stuck in a device call with SIGTERM
    pending. A TPU worker that outlives its cluster keeps the exclusive
    libtpu lock and crash-loops whatever claims the chip next — the next
    ``init()`` in this same driver process (bench phases, test suites)
    must start from a clean slate. Workers of OTHER in-process raylets
    (the Cluster harness) are left alone: victims are the raylet's own
    tracked worker pids plus direct ``worker_main`` children spawned with
    this node's id (zygote-forked workers are always tracked)."""
    import signal

    node_id_hex = raylet.node_id.hex()
    me = os.getpid()
    victims: list[int] = []
    for w in list(raylet._workers.values()):
        if w.proc is not None and w.proc.poll() is None:
            victims.append(w.proc.pid)
    try:
        entries = os.listdir("/proc")
    except OSError:
        entries = []
    for pid_dir in entries:
        if not pid_dir.isdigit():
            continue
        pid = int(pid_dir)
        if pid in victims:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[-1].split()[1])
        except (OSError, IndexError, ValueError):
            continue
        if ppid == me and "worker_main" in cmd and node_id_hex in cmd:
            victims.append(pid)
    for pid in victims:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    deadline = time.monotonic() + deadline_s
    for pid in victims:
        while time.monotonic() < deadline:
            try:
                done, _ = os.waitpid(pid, os.WNOHANG)
                if done == pid:
                    break
            except (ChildProcessError, OSError):
                # Not our child (zygote-forked, auto-reaped there): poll
                # for existence instead of waiting.
                try:
                    os.kill(pid, 0)
                except OSError:
                    break
            time.sleep(0.05)
