"""Public API: init/shutdown, @remote, get/put/wait, actors.

Equivalent of the reference's ``python/ray/_private/worker.py`` (init:1285,
get:2642, put:2810, wait:2875, remote:3263), ``remote_function.py`` and
``actor.py`` (ActorClass:605, ActorHandle:1273).
"""

from __future__ import annotations

import functools
import inspect
import os
import threading
import time
from typing import Any, Callable, Sequence

from .ids import ActorID
from .object_ref import ObjectRef
from .status import RayTpuError
from .worker import CoreWorker, global_worker, set_global_worker

_init_lock = threading.Lock()
_node = None


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    resources: dict | None = None,
    labels: dict | None = None,
    object_store_memory: int | None = None,
    ignore_reinit_error: bool = False,
    _system_config: dict | None = None,
) -> dict:
    """Start (or connect to) a cluster. Reference: worker.py:1285."""
    global _node
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                # Re-entrant init is only a no-op if the cluster this
                # process started is actually still ALIVE. A locally
                # hosted raylet can die underneath us (OOM-killed store,
                # crashed node harness): without this probe every later
                # init() no-ops against the corpse and each new worker
                # fails booting on the vanished shm store file.
                if _node is None or _local_cluster_alive(_node):
                    return {"address": _node.gcs_address if _node else address}
                if _node.gcs is None:
                    # Attached to a REMOTE cluster: rebooting would
                    # silently swap the user onto an isolated local
                    # cluster. Surface the death instead.
                    raise RayTpuError(
                        "local raylet attached to %s has died; call "
                        "ray_tpu.shutdown() then init(address=...) to "
                        "reattach" % _node.gcs_address)
                _shutdown_locked(tolerant=True)
            else:
                raise RayTpuError(
                    "ray_tpu.init() called twice; pass ignore_reinit_error=True")
        if _system_config:
            from .config import get_config

            get_config().apply_dict(_system_config)
        from .node import Node

        if address is None:
            # Submitted-job drivers connect to the running cluster via env
            # (reference: RAY_ADDRESS set by the job manager for entrypoints).
            address = os.environ.get("RAY_TPU_ADDRESS") or None
        if address is not None and address.startswith("ray://"):
            # Ray Client mode: a REMOTE driver proxied through a cluster-
            # side ClientServer (reference util/client/__init__.py:200).
            from ..util.client import connect

            set_global_worker(connect(address))
            return {"address": address, "node_id": "client"}
        if address is None:
            _node = Node(
                head=True,
                num_cpus=num_cpus,
                resources=resources,
                labels=labels,
                object_store_memory=object_store_memory,
            )
        else:
            # Connect to an existing cluster: start a local raylet joined to
            # the remote GCS (simplest driver attachment for the harness).
            _node = Node(
                head=False,
                gcs_address=address,
                num_cpus=num_cpus if num_cpus is not None else 0,
                resources=resources,
                labels=labels,
                object_store_memory=object_store_memory,
            )
        _node.connect_driver()
        return {"address": _node.gcs_address, "node_id": _node.raylet.node_id.hex()}


def is_initialized() -> bool:
    try:
        global_worker()
        return True
    except RayTpuError:
        return False


def _local_cluster_alive(node) -> bool:
    """Cheap liveness probe for the in-process cluster: the raylet's shm
    store segment must still exist (it vanishes when the store process
    dies or the node harness was torn down behind our back)."""
    try:
        return os.path.exists(node.raylet.store_path)
    except Exception:
        return False


def _shutdown_locked(tolerant: bool = False) -> None:
    """Shutdown body; caller holds ``_init_lock``. ``tolerant`` is for
    tearing down an already-dead cluster (the init liveness probe),
    where teardown steps are expected to fail; a user-called shutdown
    of a healthy cluster keeps errors loud."""
    global _node
    try:
        worker = global_worker()
        worker.shutdown()
    except RayTpuError:
        pass
    except Exception:
        if not tolerant:
            raise
    set_global_worker(None)
    if _node is not None:
        try:
            _node.shutdown()
        except Exception:
            if not tolerant:
                _node = None
                raise
        _node = None


def shutdown() -> None:
    with _init_lock:
        _shutdown_locked()


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def get(refs, timeout: float | None = None):
    from ..observability import tracing

    single = isinstance(refs, ObjectRef)
    refs = [refs] if single else list(refs)
    ctx = tracing.current()
    if ctx is not None:
        # Inside an active trace: the get is a hop worth seeing (it is
        # where submit→lease→run latency surfaces to the caller).
        with tracing.span(f"get x{len(refs)}", kind="task",
                          attrs={"num_refs": len(refs)}):
            out = global_worker().get(refs, timeout)
    else:
        out = global_worker().get(refs, timeout)
    return out[0] if single else out


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1, timeout: float | None = None):
    return global_worker().wait(list(refs), num_returns, timeout)


def kill(actor: "ActorHandle") -> None:
    global_worker().kill_actor(actor._actor_id)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task producing ``ref`` (reference ``ray.cancel``):
    queued tasks are dropped and fail with TaskCancelledError; a running
    task is interrupted at its next Python bytecode; ``force=True`` kills
    the executing worker. Best-effort — a task that already finished is
    untouched; cancelled tasks are never retried."""
    global_worker().cancel(ref, force=force)


def get_actor(name: str) -> "ActorHandle":
    found = global_worker().get_actor_by_name(name)
    if found is None:
        raise ValueError(f"No actor named '{name}'")
    actor_id, _info = found
    return ActorHandle(actor_id)


def cluster_resources() -> dict:
    worker = global_worker()
    reply = worker._gcs_call("GetAllNodes", {})
    total: dict[str, float] = {}
    for node in reply["nodes"]:
        if node["state"] != "ALIVE":
            continue
        for k, v in node["resources"]["total"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict:
    worker = global_worker()
    reply = worker._gcs_call("GetAllNodes", {})
    total: dict[str, float] = {}
    for node in reply["nodes"]:
        # A draining node (preemption notice) is about to vanish: its
        # capacity must not count as available, or the elastic train
        # policy would size a group onto a node that dies mid-attempt.
        if node["state"] != "ALIVE" or node.get("draining"):
            continue
        for k, v in node["resources"]["available"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def nodes() -> list:
    return global_worker()._gcs_call("GetAllNodes", {})["nodes"]


class RuntimeContext:
    """Reference: ``python/ray/runtime_context.py`` (get_runtime_context)."""

    @property
    def node_id(self) -> str:
        return global_worker().node_id

    @property
    def worker_id(self) -> str:
        return global_worker().worker_id

    @property
    def job_id(self) -> int:
        return global_worker().job_id.int_value()

    @property
    def actor_id(self) -> str | None:
        aid = global_worker().actor_id
        return aid.hex() if aid else None

    def get_node_id(self) -> str:
        return self.node_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def timeline(filename: str = "timeline.json") -> str:
    """Dump a chrome://tracing / Perfetto trace of task execution
    (reference ``ray.timeline``, ``python/ray/_private/state.py:965``)."""
    from .task_events import write_chrome_trace

    reply = global_worker()._gcs_call("Timeline", {})
    return write_chrome_trace(reply["trace"], filename)


# ----------------------------------------------------------------- @remote
_ABSENT = object()


class RemoteFunction:
    """Reference: remote_function.py (_remote:303)."""

    def __init__(self, fn: Callable, **options):
        self._fn = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def options(self, **new_options) -> "RemoteFunction":
        merged = {**self._options, **new_options}
        return RemoteFunction(self._fn, **merged)

    def _remote(self, args, kwargs, opts):
        worker = global_worker()
        resources = dict(opts.get("resources") or {})
        if opts.get("num_cpus") is not None:
            resources["CPU"] = opts["num_cpus"]
        if opts.get("num_tpus") is not None:
            resources["TPU"] = opts["num_tpus"]
        strategy = _strategy_to_wire(opts.get("scheduling_strategy"))
        pg_id, bundle = _placement_opts(opts)
        num_returns = opts.get("num_returns", 1)
        refs = worker.submit_task(
            self._fn,
            args,
            kwargs,
            name=opts.get("name") or self._fn.__name__,
            num_returns=num_returns,
            resources=resources,
            max_retries=opts.get("max_retries"),
            scheduling_strategy=strategy,
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle,
            runtime_env=opts.get("runtime_env"),
            generator_backpressure=opts.get("_generator_backpressure_num_objects") or 0,
        )
        if num_returns == "streaming":
            return refs  # ObjectRefGenerator
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use .remote()."
        )


class ActorMethod:
    """Reference: actor.py:116."""

    def __init__(self, handle: "ActorHandle", name: str, num_returns: int | str = 1,
                 generator_backpressure: int = 0, concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._generator_backpressure = generator_backpressure
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        refs = global_worker().submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            generator_backpressure=self._generator_backpressure,
            concurrency_group=self._concurrency_group,
        )
        if self._num_returns == "streaming":
            return refs  # ObjectRefGenerator
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns: int | str = 1,
                _generator_backpressure_num_objects: int = 0,
                concurrency_group: str = "") -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns,
                           _generator_backpressure_num_objects,
                           concurrency_group or self._concurrency_group)

    def bind(self, *args, **kwargs):
        """Build a compiled-graph node instead of submitting now
        (reference ``dag/class_node.py`` ClassMethodNode)."""
        from ..dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    """Reference: actor.py:1273. Pickles to the actor id; any process with
    the handle can call methods (per-caller sequencing actor-side). The
    owning process kills a non-detached, unnamed actor when its last local
    handle is garbage-collected."""

    def __init__(self, actor_id: bytes, _owned: bool = False):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_registered", False)
        try:
            global_worker().register_actor_handle(actor_id, _owned)
            object.__setattr__(self, "_registered", True)
        except RayTpuError:
            pass

    def __getattr__(self, item: str) -> ActorMethod:
        if item == "__ray_call__":
            # Internal: run a shipped function on the actor (compiled DAGs
            # install their executor loops through this).
            return ActorMethod(self, item)
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id,))

    def __del__(self):
        if getattr(self, "_registered", False):
            try:
                global_worker().deregister_actor_handle(self._actor_id)
            except Exception:
                pass

    def __repr__(self):
        return f"ActorHandle({ActorID(self._actor_id).hex()})"


class ActorClass:
    """Reference: actor.py:605."""

    def __init__(self, cls: type, **options):
        self._cls = cls
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = global_worker()
        opts = self._options
        resources = dict(opts.get("resources") or {})
        if opts.get("num_tpus") is not None:
            resources["TPU"] = opts["num_tpus"]
        strategy = _strategy_to_wire(opts.get("scheduling_strategy"))
        pg_id, bundle = _placement_opts(opts)
        actor_id = worker.create_actor(
            self._cls,
            args,
            kwargs,
            name=opts.get("name", ""),
            num_cpus=opts.get("num_cpus"),
            resources=resources,
            max_restarts=opts.get("max_restarts", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            concurrency_groups=opts.get("concurrency_groups"),
            detached=opts.get("lifetime") == "detached",
            scheduling_strategy=strategy,
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle,
            runtime_env=opts.get("runtime_env"),
        )
        # Non-detached actors — named or not — die when the creator's last
        # handle is GC'd (reference actor.py: only lifetime="detached"
        # survives its creator).
        owned = opts.get("lifetime") != "detached"
        return ActorHandle(actor_id, _owned=owned)

    def options(self, **new_options) -> "ActorClass":
        return ActorClass(self._cls, **{**self._options, **new_options})


def _strategy_to_wire(strategy) -> dict:
    if strategy is None:
        return {}
    if isinstance(strategy, dict):
        return strategy
    return strategy.to_wire()


def _placement_opts(opts) -> tuple[bytes, int]:
    strategy = opts.get("scheduling_strategy")
    if strategy is not None and hasattr(strategy, "placement_group_id"):
        return strategy.placement_group_id, strategy.placement_group_bundle_index
    return b"", -1


def remote(*args, **options):
    """``@remote`` / ``@remote(num_cpus=..., ...)`` for functions and classes."""

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    if len(args) == 1 and not options and (callable(args[0]) or inspect.isclass(args[0])):
        return wrap(args[0])
    return wrap


def method(num_returns: int = 1, concurrency_group: str = ""):
    """Per-method defaults on actor classes (reference actor.py
    ``@ray.method``): ``concurrency_group`` names the pool declared in
    ``@remote(concurrency_groups={...})`` this method runs in —
    resolved executor-side from the class definition, so handles need
    not know the class."""

    def decorator(fn):
        fn.__ray_num_returns__ = num_returns
        if concurrency_group:
            fn.__ray_concurrency_group__ = concurrency_group
        return fn

    return decorator
