"""Task events: per-task status timestamps buffered and flushed to the GCS.

Equivalent of the reference's ``TaskEventBuffer``
(``src/ray/core_worker/task_event_buffer.h:224,300``) feeding
``GcsTaskManager``: every worker batches status transitions
(SUBMITTED/LEASED/RUNNING/FINISHED/FAILED) and flushes them on an
interval; the GCS keeps a bounded ring of events that powers the state
API (``list_tasks``) and the chrome-trace timeline (``ray_tpu.timeline()``,
reference ``python/ray/_private/state.py:965``).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any

# Status transition names (reference rpc::TaskStatus).
SUBMITTED = "SUBMITTED"
LEASED = "LEASED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
# Pseudo-status carrying a finished trace span (observability/tracing.py)
# through the same buffered flush path; the GCS routes these to its span
# store instead of the task table.
SPAN = "SPAN"
# Pseudo-status carrying a worker memory summary (observability/memory.py)
# on the same flush path; the GCS routes these to its memory store.
MEMORY = "MEMORY"


def _resolve_state(events: dict) -> str:
    if FAILED in events:
        return FAILED
    if FINISHED in events:
        return FINISHED
    if RUNNING in events:
        return RUNNING
    if LEASED in events:
        return LEASED
    return SUBMITTED


# Fields every status event carries; anything else is a per-transition
# extra (error, trace_id, queue_wait_ms, a LEASED worker_id override...)
# and must survive coalescing on the transition entry itself.
_BASE_KEYS = ("task_id", "name", "status", "ts", "worker_id", "node_id", "kind")


def coalesce_events(events: list[dict], window_ms: float) -> list[dict]:
    """Merge one task's status transitions recorded within ``window_ms``
    into ONE wire event carrying a ``transitions`` list — a task that ran
    SUBMITTED→LEASED→FINISHED inside a flush interval ships as one dict
    instead of three. SPAN/MEMORY pseudo-events never coalesce (the GCS
    routes them to different stores). The GCS replays transitions in
    recorded order, so per-task records and the lease-stage histograms
    are byte-identical to the unbatched path."""
    window_s = window_ms / 1000.0
    out: list[dict] = []
    open_groups: dict[str, dict] = {}  # task_id -> coalesced event
    for ev in events:
        status = ev.get("status")
        tid = ev.get("task_id")
        if status in (SPAN, MEMORY) or not tid:
            out.append(ev)
            continue
        extras = {k: v for k, v in ev.items() if k not in _BASE_KEYS}
        # worker_id varies per transition on the owner's LEASED records:
        # keep any value that differs from the group base.
        group = open_groups.get(tid)
        if group is not None and ev["ts"] - group["transitions"][0]["ts"] > window_s:
            group = None  # beyond the window: start a fresh group
        if group is None:
            group = dict(ev)
            group.pop("status", None)
            for k in list(extras):
                group.pop(k, None)
            group["transitions"] = []
            open_groups[tid] = group
            out.append(group)
        tr = {"status": status, "ts": ev["ts"]}
        if ev.get("worker_id") != group.get("worker_id"):
            tr["worker_id"] = ev.get("worker_id")
        tr.update(extras)
        group["transitions"].append(tr)
        # The wire dict stays a valid single event too (status/ts = the
        # latest transition) so foreign consumers that predate coalescing
        # still see a sane record.
        group["status"] = status
        group["ts"] = ev["ts"]
    return out


def expand_event(ev: dict) -> list[dict]:
    """Inverse of :func:`coalesce_events` for one wire event: yield one
    plain event per transition (transition fields override the base)."""
    transitions = ev.get("transitions")
    if not transitions:
        return [ev]
    base = {k: v for k, v in ev.items() if k != "transitions"}
    out = []
    for tr in transitions:
        e = dict(base)
        e.update(tr)
        out.append(e)
    return out


class TaskEventBuffer:
    """Worker-side bounded buffer of task status events."""

    def __init__(self, worker_id: str, node_id: str, max_buffer: int = 10_000):
        self._worker_id = worker_id
        self._node_id = node_id
        self._max = max_buffer
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._dropped = 0

    def record(self, task_id: bytes, name: str, status: str, *,
               kind: int = 0, extra: dict | None = None) -> None:
        ev = {
            "task_id": task_id.hex() if isinstance(task_id, bytes) else task_id,
            "name": name,
            "status": status,
            "ts": time.time(),
            "worker_id": self._worker_id,
            "node_id": self._node_id,
            "kind": kind,
        }
        if extra:
            ev.update(extra)
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def record_span(self, span: dict) -> None:
        """Buffer one finished trace span; it rides the same drain/flush
        batch as status events (status ``SPAN``)."""
        ev = {
            "task_id": span.get("trace_id", ""),
            "name": span.get("name", ""),
            "status": SPAN,
            "ts": span.get("end", time.time()),
            "worker_id": self._worker_id,
            "node_id": self._node_id,
            "kind": 0,
            "span": span,
        }
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def record_memory(self, summary: dict) -> None:
        """Buffer one per-worker memory summary; rides the same drain/flush
        batch as status events (status ``MEMORY``)."""
        ev = {
            "task_id": "",
            "name": "memory_summary",
            "status": MEMORY,
            "ts": summary.get("ts", time.time()),
            "worker_id": self._worker_id,
            "node_id": self._node_id,
            "kind": 0,
            "memory": summary,
        }
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def drain(self, coalesce_window_ms: float | None = None) -> tuple[list[dict], int]:
        """Take the buffered events. ``coalesce_window_ms`` (None = read
        the config knob) > 0 merges each task's transitions into one wire
        event — the flush RPC ships and the GCS ingests a fraction of the
        dicts for the same information."""
        with self._lock:
            events, self._events = self._events, []
            dropped, self._dropped = self._dropped, 0
        if coalesce_window_ms is None:
            from .config import get_config

            coalesce_window_ms = get_config().task_event_coalesce_ms
        if coalesce_window_ms and coalesce_window_ms > 0 and len(events) > 1:
            events = coalesce_events(events, coalesce_window_ms)
        return events, dropped


class _TaskShard:
    __slots__ = ("lock", "tasks")

    def __init__(self):
        self.lock = threading.Lock()
        # dict insertion order IS the per-shard ring order; records carry
        # a global "_seq" stamp so merged listings reconstruct the exact
        # 1-shard insertion order.
        self.tasks: dict[str, dict] = {}


class GcsTaskEventStore:
    """GCS-side bounded event log + per-task aggregation
    (reference ``gcs_task_manager.h``), SHARDED by task-id hash (the
    ``store_client/`` treatment): each shard has its own lock, so N
    raylets' flush batches ingest concurrently instead of convoying on
    one store lock, while per-task reads/writes stay linearizable (a
    task id always lands in exactly one shard). Listings merge across
    shards by global sequence stamp — byte-identical to the 1-shard
    store for the same input order."""

    def __init__(self, max_tasks: int = 100_000, on_stage=None,
                 shards: int | None = None):
        if shards is None:
            from .config import get_config

            shards = get_config().gcs_store_shards
        from .store_client import shard_index

        self._shard_index = shard_index
        self._n = max(1, int(shards))
        self._shards = [_TaskShard() for _ in range(self._n)]
        self._seq = itertools.count(1)
        self._max = max_tasks
        self.num_dropped = 0
        self._dropped_lock = threading.Lock()
        # Optional (stage, duration_ms, node_id) observer fed at ingest:
        # backs the per-raylet lease-stage histograms without a second
        # pass over the event log.
        self._on_stage = on_stage

    def add_events(self, events: list[dict], dropped: int = 0) -> None:
        # Coalesced events expand to their individual transitions here,
        # applied in recorded order, so the store (and the stage
        # observer) sees exactly the sequence the unbatched path would
        # have delivered. Each event takes only its own shard's lock.
        if dropped:
            with self._dropped_lock:
                self.num_dropped += dropped
        for wire in events:
            if wire.get("transitions"):
                for ev in expand_event(wire):
                    self._ingest(ev)
            else:
                self._ingest(wire)

    def _ingest(self, ev: dict) -> None:
        tid = ev["task_id"]
        if isinstance(tid, bytes):
            # Normalize at ingest: every reporter (worker buffer,
            # raylet, GCS-side stamps) must land on ONE record per
            # task, whatever id form it sends.
            tid = tid.hex()
        status = ev["status"]
        ts = ev["ts"]
        shard = self._shards[self._shard_index(tid, self._n)]
        with shard.lock:
            rec = shard.tasks.get(tid)
            if rec is None:
                rec = shard.tasks[tid] = {
                    "task_id": tid,
                    "name": ev.get("name", ""),
                    "kind": ev.get("kind", 0),
                    "events": {},
                    "_seq": next(self._seq),
                }
            self._observe_stages(rec, ev, status, ts)
            if status == LEASED:
                # Both the raylet (at grant) and the owner (at
                # dispatch) report LEASED: keep the earliest — the
                # actual grant time.
                rec["events"].setdefault(status, ts)
            else:
                rec["events"][status] = ts
            rec["name"] = ev.get("name") or rec["name"]
            for key in ("worker_id", "node_id", "error", "trace_id"):
                if ev.get(key):
                    rec[key] = ev[key]
        self._maybe_evict()

    def _maybe_evict(self) -> None:
        """Evict the globally-oldest record once over capacity — the same
        record the 1-shard ring would pop (its _seq is the global
        insertion order), found by peeking each shard's own oldest."""
        while sum(len(s.tasks) for s in self._shards) > self._max:
            oldest: tuple[int, _TaskShard, str] | None = None
            for shard in self._shards:
                with shard.lock:
                    head = next(iter(shard.tasks), None)
                    if head is None:
                        continue
                    seq = shard.tasks[head]["_seq"]
                if oldest is None or seq < oldest[0]:
                    oldest = (seq, shard, head)
            if oldest is None:
                return
            _, shard, tid = oldest
            with shard.lock:
                shard.tasks.pop(tid, None)

    def _observe_stages(self, rec: dict, ev: dict, status: str, ts: float) -> None:
        if self._on_stage is None:
            return
        node = ev.get("node_id", "")
        # Raylet-measured sub-stages ride the LEASED event itself.
        for key, stage in (("queue_wait_ms", "lease_queue_wait"),
                           ("spawn_ms", "worker_spawn")):
            if ev.get(key) is not None:
                self._on_stage(stage, float(ev[key]), node)
        events = rec["events"]
        if status == LEASED and LEASED not in events and SUBMITTED in events:
            self._on_stage("submit_to_lease", (ts - events[SUBMITTED]) * 1000.0, node)
        elif status == RUNNING and RUNNING not in events and LEASED in events:
            self._on_stage("lease_to_run", (ts - events[LEASED]) * 1000.0, node)

    def list_tasks(self, limit: int = 1000) -> list[dict]:
        # Merge shards by global sequence stamp: the exact insertion
        # order the 1-shard ring would have listed.
        rows: list[tuple[int, dict]] = []
        for shard in self._shards:
            with shard.lock:
                rows.extend((rec["_seq"], rec) for rec in shard.tasks.values())
        rows.sort(key=lambda r: r[0])
        out = []
        for _, rec in rows[-limit:] if limit else rows:
            events = rec["events"]
            out.append({
                "task_id": rec["task_id"],
                "name": rec["name"],
                "state": _resolve_state(events),
                "kind": rec.get("kind", 0),
                "worker_id": rec.get("worker_id", ""),
                "node_id": rec.get("node_id", ""),
                "error": rec.get("error", ""),
                "trace_id": rec.get("trace_id", ""),
                "events": dict(events),
            })
        return out

    def count_by_state(self) -> dict[str, int]:
        """State tallies without materializing record copies (metrics
        scrapes poll this every few seconds)."""
        out: dict[str, int] = {}
        for shard in self._shards:
            with shard.lock:
                for rec in shard.tasks.values():
                    state = _resolve_state(rec["events"])
                    out[state] = out.get(state, 0) + 1
        return out

    def chrome_trace(self) -> list[dict]:
        """Events in the chrome://tracing (Perfetto) JSON array format
        (reference ``state.py chrome_tracing_dump:442``)."""
        trace: list[dict] = []
        for rec in self.list_tasks(limit=self._max):
            events = rec["events"]
            start = events.get(RUNNING) or events.get(SUBMITTED)
            end = events.get(FINISHED) or events.get(FAILED)
            if start is None:
                continue
            dur_us = max(1.0, ((end or time.time()) - start) * 1e6)
            trace.append({
                "name": rec["name"],
                "cat": "task",
                "ph": "X",
                "ts": start * 1e6,
                "dur": dur_us,
                "pid": f"node:{rec.get('node_id', '?')[:8]}",
                "tid": f"worker:{rec.get('worker_id', '?')[:8]}",
                "args": {"task_id": rec["task_id"], "state": rec["state"],
                         "trace_id": rec.get("trace_id", "")},
            })
        return trace


def write_chrome_trace(events: list[dict], filename: str) -> str:
    with open(filename, "w") as f:
        json.dump(events, f)
    return filename
