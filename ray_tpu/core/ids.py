"""Binary identifiers with embedded lineage.

TPU-native re-design of the reference's ID scheme
(``src/ray/common/id.h:109-341``): IDs are fixed-width byte strings where a
child ID embeds its parent's ID so lineage can be recovered from the ID alone:

  JobID   (4 bytes)   — per driver / job
  ActorID (16 bytes)  — 12 unique bytes + JobID
  TaskID  (24 bytes)  — 8 unique bytes + ActorID (nil actor for normal tasks)
  ObjectID(28 bytes)  — TaskID + 4-byte little-endian return/put index
  NodeID, WorkerID, PlacementGroupID (28/28/18 bytes) — random

Task IDs are generated deterministically from (parent task, counter) so that
lineage re-execution regenerates identical object IDs — the property the
reference relies on for reconstruction (``task_spec.h:257``).
"""

from __future__ import annotations

import hashlib
import os
import struct

_NIL = b"\xff"


class BaseID:
    SIZE: int = 28
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = bytes(binary)
        self._hash = hash((type(self).__name__, self._bytes))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == _NIL * self.SIZE

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class UniqueID(BaseID):
    SIZE = 28


class NodeID(BaseID):
    SIZE = 28


class WorkerID(BaseID):
    SIZE = 28


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack("<I", value))

    def int_value(self) -> int:
        return struct.unpack("<I", self._bytes)[0]


class ActorID(BaseID):
    """12 unique bytes + 4-byte JobID (reference ``id.h:130``)."""

    SIZE = 16
    UNIQUE_BYTES = 12

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", parent_task_counter: int) -> "ActorID":
        h = hashlib.sha1()
        h.update(parent_task_id.binary())
        h.update(struct.pack("<Q", parent_task_counter))
        return cls(h.digest()[: cls.UNIQUE_BYTES] + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])


class TaskID(BaseID):
    """8 unique bytes + 16-byte ActorID (reference ``id.h:178``)."""

    SIZE = 24
    UNIQUE_BYTES = 8

    @classmethod
    def for_driver_task(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x00" * cls.UNIQUE_BYTES + ActorID.nil().binary()[:12] + job_id.binary())

    @classmethod
    def for_normal_task(
        cls, job_id: JobID, parent_task_id: "TaskID", parent_task_counter: int
    ) -> "TaskID":
        h = hashlib.sha1()
        h.update(parent_task_id.binary())
        h.update(struct.pack("<Q", parent_task_counter))
        nil_actor = ActorID.nil().binary()[: ActorID.UNIQUE_BYTES]
        return cls(h.digest()[: cls.UNIQUE_BYTES] + nil_actor + job_id.binary())

    @classmethod
    def for_actor_creation_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(b"\x00" * cls.UNIQUE_BYTES + actor_id.binary())

    @classmethod
    def for_actor_task(
        cls,
        job_id: JobID,
        parent_task_id: "TaskID",
        parent_task_counter: int,
        actor_id: ActorID,
    ) -> "TaskID":
        h = hashlib.sha1()
        h.update(parent_task_id.binary())
        h.update(struct.pack("<Q", parent_task_counter))
        return cls(h.digest()[: cls.UNIQUE_BYTES] + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[self.UNIQUE_BYTES :])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    """TaskID + 4-byte index (reference ``id.h:264``).

    Index 1..N are task returns; put objects use a separate counter offset by
    ``PUT_INDEX_OFFSET`` so returns and puts never collide.
    """

    SIZE = 28
    PUT_INDEX_OFFSET = 1 << 24

    @classmethod
    def for_task_return(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", return_index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", put_index + cls.PUT_INDEX_OFFSET))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[TaskID.SIZE :])[0]

    def is_put(self) -> bool:
        return self.index() >= self.PUT_INDEX_OFFSET

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class PlacementGroupID(BaseID):
    """14 unique bytes + JobID (reference ``id.h:341``)."""

    SIZE = 18
    UNIQUE_BYTES = 14

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(cls.UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])
