"""Sharded control-plane stores (reference ``src/ray/gcs/store_client/``).

The GCS serialized every task-event, actor, and KV write through one
lock (and, worse, through its single event loop) — N raylets flushing
task events convoyed on each other and on every heartbeat. The split
here mirrors the reference's ``store_client/`` layering: a key-hashed
shard layout with ONE lock per shard, so concurrent writers touching
different keys never contend, while reads stay linearizable per key
(a key always lives in exactly one shard, guarded by that shard's lock).

Cross-shard ordering is preserved where consumers can observe it: every
record carries a global monotone sequence stamp, and merged listings
sort by it — so an N-shard store's ``list``/iteration output is
byte-identical to the 1-shard store's insertion order (the PR-6d
equivalence-test treatment, re-applied to sharding).
"""

from __future__ import annotations

import itertools
import threading
import zlib
from typing import Any, Iterator, MutableMapping


def shard_index(key: Any, num_shards: int) -> int:
    """Stable key -> shard routing (crc32: identical across processes
    and runs, unlike ``hash`` under PYTHONHASHSEED)."""
    if num_shards <= 1:
        return 0
    if isinstance(key, bytes):
        raw = key
    else:
        raw = str(key).encode()
    return zlib.crc32(raw) % num_shards


class _KvShard:
    __slots__ = ("lock", "items")

    def __init__(self):
        self.lock = threading.Lock()
        # key -> (seq, value); seq is the global insertion stamp used to
        # reconstruct 1-shard iteration order in merged views.
        self.items: dict[Any, tuple[int, Any]] = {}


class ShardedKv(MutableMapping):
    """A MutableMapping sharded by key hash with per-shard locks.

    Drop-in for the GCS ``_kv`` / ``_actors`` dict tables: point reads
    and writes take exactly one shard lock; iteration / ``keys(prefix)``
    merge across shards in global insertion order, so snapshot and
    restore see the same ordering a plain dict gave.
    """

    def __init__(self, num_shards: int = 8, initial: dict | None = None):
        self._n = max(1, int(num_shards))
        self._shards = [_KvShard() for _ in range(self._n)]
        self._seq = itertools.count(1)  # .__next__ is atomic in CPython
        if initial:
            for k, v in initial.items():
                self[k] = v

    # ------------------------------------------------------------ mapping
    def _shard(self, key: Any) -> _KvShard:
        return self._shards[shard_index(key, self._n)]

    def __getitem__(self, key: Any) -> Any:
        shard = self._shard(key)
        with shard.lock:
            return shard.items[key][1]

    def __setitem__(self, key: Any, value: Any) -> None:
        shard = self._shard(key)
        with shard.lock:
            prev = shard.items.get(key)
            # Overwrites keep their original position, like a dict.
            seq = prev[0] if prev is not None else next(self._seq)
            shard.items[key] = (seq, value)

    def __delitem__(self, key: Any) -> None:
        shard = self._shard(key)
        with shard.lock:
            del shard.items[key]

    def __contains__(self, key: Any) -> bool:
        shard = self._shard(key)
        with shard.lock:
            return key in shard.items

    def __len__(self) -> int:
        return sum(len(s.items) for s in self._shards)

    def __iter__(self) -> Iterator:
        return iter([k for k, _ in self._merged()])

    def _merged(self) -> list[tuple[Any, Any]]:
        rows: list[tuple[int, Any, Any]] = []
        for shard in self._shards:
            with shard.lock:
                rows.extend((seq, k, v) for k, (seq, v) in shard.items.items())
        rows.sort(key=lambda r: r[0])
        return [(k, v) for _, k, v in rows]

    # dict-parity conveniences used by the GCS tables
    def values(self):
        return [v for _, v in self._merged()]

    def items(self):
        return self._merged()

    def keys(self):
        return [k for k, _ in self._merged()]

    def to_dict(self) -> dict:
        """Plain-dict snapshot in insertion order (persistence path)."""
        return dict(self._merged())

    def keys_with_prefix(self, prefix: str) -> list:
        return [k for k, _ in self._merged()
                if isinstance(k, str) and k.startswith(prefix)]
