"""Resource model: fixed-point quantities, resource sets, node resources.

Equivalent of the reference's ``src/ray/common/scheduling/``:
``FixedPoint`` (``fixed_point.h``) avoids float drift in repeated
acquire/release; ``ResourceSet``/``NodeResources``
(``cluster_resource_data.h``) model predefined (CPU/memory/TPU/
object_store_memory) plus custom and label resources. The TPU build adds
first-class ``TPU`` chip resources and ``TPU-{type}-head`` slice-head
resources (reference ``python/ray/_private/accelerators/tpu.py:70-192``).
"""

from __future__ import annotations

from typing import Iterable

RESOURCE_UNIT = 10000  # 1.0 CPU == 10000 units (reference fixed_point.h)

CPU = "CPU"
MEMORY = "memory"
TPU = "TPU"
OBJECT_STORE_MEMORY = "object_store_memory"
PREDEFINED = (CPU, MEMORY, TPU, OBJECT_STORE_MEMORY)


def to_fixed(value: float) -> int:
    return int(round(value * RESOURCE_UNIT))


def from_fixed(units: int) -> float:
    return units / RESOURCE_UNIT


class ResourceSet:
    """A bag of named resource quantities in fixed-point units."""

    __slots__ = ("_units",)

    def __init__(self, amounts: dict[str, float] | None = None, *, _units: dict[str, int] | None = None):
        if _units is not None:
            self._units = {k: v for k, v in _units.items() if v != 0}
        else:
            self._units = {}
            for name, value in (amounts or {}).items():
                units = to_fixed(value)
                if units != 0:
                    self._units[name] = units

    # -- accessors -----------------------------------------------------------
    def get(self, name: str) -> float:
        return from_fixed(self._units.get(name, 0))

    def get_units(self, name: str) -> int:
        return self._units.get(name, 0)

    def names(self) -> Iterable[str]:
        return self._units.keys()

    def is_empty(self) -> bool:
        return not self._units

    def to_dict(self) -> dict[str, float]:
        return {k: from_fixed(v) for k, v in self._units.items()}

    # -- algebra -------------------------------------------------------------
    def subset_of(self, other: "ResourceSet") -> bool:
        return all(other._units.get(k, 0) >= v for k, v in self._units.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            units[k] = units.get(k, 0) + v
        return ResourceSet(_units=units)

    def subtract(self, other: "ResourceSet", *, allow_negative: bool = False) -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            nv = units.get(k, 0) - v
            if nv < 0 and not allow_negative:
                raise ValueError(f"Resource {k} would go negative")
            units[k] = nv
        return ResourceSet(_units=units)

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSet) and self._units == other._units

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"


class NodeResources:
    """Total and available resources plus labels for one node.

    Mirrors ``NodeResources`` in ``cluster_resource_data.h``; labels support
    the node-label scheduling policy and TPU slice/generation affinity.
    """

    def __init__(self, total: dict[str, float], labels: dict[str, str] | None = None):
        self.total = ResourceSet(total)
        self.available = ResourceSet(total)
        self.labels = dict(labels or {})

    def can_fit(self, request: ResourceSet) -> bool:
        return request.subset_of(self.available)

    def could_ever_fit(self, request: ResourceSet) -> bool:
        return request.subset_of(self.total)

    def acquire(self, request: ResourceSet) -> None:
        self.available = self.available.subtract(request)

    def release(self, request: ResourceSet) -> None:
        self.available = self.available.add(request)

    def utilization(self) -> float:
        """Max over resources of used/total — the hybrid policy's node score
        (reference ``hybrid_scheduling_policy.cc``)."""
        score = 0.0
        for name in self.total.names():
            total = self.total.get_units(name)
            if total <= 0:
                continue
            used = total - self.available.get_units(name)
            score = max(score, used / total)
        return score

    def to_dict(self) -> dict:
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": self.labels,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NodeResources":
        nr = cls(d["total"], d.get("labels"))
        nr.available = ResourceSet(d["available"])
        return nr
