"""Task specification: the unit handed from owner → scheduler → worker.

Equivalent of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h:257``) minus protobuf: a plain dict
(msgpack-encodable) so it crosses the RPC layer untouched. Function bodies
are NOT in the spec — they live in the GCS function table keyed by
``function_id`` (reference ``python/ray/_private/function_manager.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

TASK_KIND_NORMAL = 0
TASK_KIND_ACTOR_CREATION = 1
TASK_KIND_ACTOR_TASK = 2


@dataclass
class TaskSpec:
    task_id: bytes
    job_id: bytes
    name: str
    function_id: bytes  # GCS function-table key
    kind: int = TASK_KIND_NORMAL
    # Serialized args: list of dicts
    #   {"t": "v", "meta": bytes, "blob": bytes}                — inline value
    #   {"t": "r", "id": bytes, "owner": str}                   — ObjectRef arg
    args: list = field(default_factory=list)
    # -1 = streaming generator (``num_returns="streaming"``): returns are
    # reported item-by-item while the task runs (reference _raylet.pyx:294).
    num_returns: int = 1
    generator_backpressure: int = 0  # 0 = unbounded
    resources: dict = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # Owner info so executors/raylets can report back / locate values.
    owner_address: str = ""
    parent_task_id: bytes = b""
    # Actor fields.
    actor_id: bytes = b""
    actor_method: str = ""
    seq_no: int = -1
    max_restarts: int = 0
    max_concurrency: int = 1
    # Named per-method concurrency pools (reference
    # concurrency_group_manager.cc): creation carries the group table,
    # each actor task the group it runs in ("" = default pool).
    concurrency_groups: dict = field(default_factory=dict)
    concurrency_group: str = ""
    # Scheduling.
    scheduling_strategy: dict = field(default_factory=dict)
    placement_group_id: bytes = b""
    placement_group_bundle_index: int = -1
    label_selector: dict = field(default_factory=dict)
    runtime_env: dict = field(default_factory=dict)
    # Distributed-trace context (observability/tracing.py): the task's
    # own span id plus its parent, propagated owner → raylet → executor
    # so every hop records into one connected span tree.
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""

    def to_wire(self) -> dict[str, Any]:
        return {
            "task_id": self.task_id,
            "job_id": self.job_id,
            "name": self.name,
            "function_id": self.function_id,
            "kind": self.kind,
            "args": self.args,
            "num_returns": self.num_returns,
            "generator_backpressure": self.generator_backpressure,
            "resources": self.resources,
            "max_retries": self.max_retries,
            "retry_exceptions": self.retry_exceptions,
            "owner_address": self.owner_address,
            "parent_task_id": self.parent_task_id,
            "actor_id": self.actor_id,
            "actor_method": self.actor_method,
            "seq_no": self.seq_no,
            "max_restarts": self.max_restarts,
            "max_concurrency": self.max_concurrency,
            "concurrency_groups": self.concurrency_groups,
            "concurrency_group": self.concurrency_group,
            "scheduling_strategy": self.scheduling_strategy,
            "placement_group_id": self.placement_group_id,
            "placement_group_bundle_index": self.placement_group_bundle_index,
            "label_selector": self.label_selector,
            "runtime_env": self.runtime_env,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "TaskSpec":
        return cls(**d)

    def required_resources(self) -> dict:
        if self.kind == TASK_KIND_ACTOR_TASK:
            return {}  # actor tasks run on the actor's existing worker
        res = dict(self.resources)
        if self.kind == TASK_KIND_NORMAL and not res:
            res = {"CPU": 1.0}
        return res

    def is_actor_creation(self) -> bool:
        return self.kind == TASK_KIND_ACTOR_CREATION

    def is_actor_task(self) -> bool:
        return self.kind == TASK_KIND_ACTOR_TASK
