"""Distributed reference counting (owner side).

Equivalent of the reference's ``ReferenceCounter``
(``src/ray/core_worker/reference_count.h:66``): per-object counts of

  * local refs        — live Python ``ObjectRef`` instances in this process
  * submitted refs    — in-flight tasks that take the object as an arg
  * contained refs    — objects serialized inside other objects (nesting)
  * borrower count    — other workers holding a deserialized copy of the ref

When all counts reach zero the owner frees the object: memory-store entry
dropped, plasma copies deleted on every node that reported a location, and
lineage unpinned. Borrowing here is a simplified variant of the reference
protocol: a borrower reports itself to the owner on deserialization and
sends a single release when its local count drains (the reference batches
this via ``WaitForRefRemoved`` pub/sub).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from .ids import ObjectID


@dataclass
class _Ref:
    local: int = 0
    submitted: int = 0
    borrowers: int = 0
    contained_in: int = 0
    # Object IDs this object's value contains (nested refs).
    contains: set = field(default_factory=set)
    # Nodes known to hold a plasma copy.
    locations: set = field(default_factory=set)
    owned: bool = False
    lineage_pinned: bool = False
    # For non-owned (borrowed) refs: the owner's RPC address, so the last
    # local release can send RemoveBorrower back to the owner.
    owner_address: str = ""
    # Borrow registration with the owner has been initiated.
    borrow_registered: bool = False
    # Memory observability (observability/memory.py): Python creation
    # callsite, serialized size, and entry age for memory_summary().
    callsite: str = ""
    size: int = 0
    created_at: float = 0.0

    def total(self) -> int:
        return self.local + self.submitted + self.borrowers + self.contained_in


class ReferenceCounter:
    def __init__(self, on_object_freed: Callable[[ObjectID, set], None] | None = None):
        self._lock = threading.RLock()
        self._refs: dict[ObjectID, _Ref] = {}
        self._on_object_freed = on_object_freed

    def _entry(self, oid: ObjectID) -> _Ref:
        ref = self._refs.get(oid)
        if ref is None:
            ref = self._refs[oid] = _Ref(created_at=time.time())
        return ref

    # -- memory observability ------------------------------------------------
    def set_callsite(self, oid: ObjectID, callsite: str) -> None:
        """First recorded callsite wins: it names the creation line, not
        later touches."""
        if not callsite:
            return
        with self._lock:
            ref = self._refs.get(oid)
            if ref is not None and not ref.callsite:
                ref.callsite = callsite

    def set_size(self, oid: ObjectID, nbytes: int) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is not None:
                ref.size = int(nbytes)

    def summary(self, limit: int = 200) -> tuple[list[dict], int, int]:
        """(entries, num_refs, total_bytes) for memory_summary(): every
        live entry classified per observability.memory.classify_ref,
        biggest first, capped at ``limit`` rows (totals are uncapped)."""
        from ..observability.memory import classify_ref

        now = time.time()
        entries: list[dict] = []
        total_bytes = 0
        with self._lock:
            num_refs = len(self._refs)
            for oid, ref in self._refs.items():
                total_bytes += ref.size
                entries.append({
                    "object_id": oid.hex(),
                    "size": ref.size,
                    "ref_type": classify_ref(
                        local=ref.local, submitted=ref.submitted,
                        contained_in=ref.contained_in,
                        borrowers=ref.borrowers,
                        pinned=bool(ref.locations)),
                    "callsite": ref.callsite,
                    "age_s": max(0.0, now - ref.created_at) if ref.created_at else 0.0,
                    "local": ref.local,
                    "submitted": ref.submitted,
                    "borrowers": ref.borrowers,
                    "contained_in": ref.contained_in,
                    "owned": ref.owned,
                })
        entries.sort(key=lambda e: e["size"], reverse=True)
        return entries[:limit], num_refs, total_bytes

    # -- ownership -----------------------------------------------------------
    def add_owned_object(self, oid: ObjectID, contained: list[ObjectID] | None = None) -> None:
        with self._lock:
            ref = self._entry(oid)
            ref.owned = True
            for child in contained or []:
                ref.contains.add(child)
                self._entry(child).contained_in += 1

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(oid)
            return bool(ref and ref.owned)

    def note_borrowed(self, oid: ObjectID, owner_address: str) -> bool:
        """Record that this process borrows ``oid`` from ``owner_address``.
        Returns True exactly once per borrow episode — the caller must then
        send AddBorrower to the owner (reference: borrower registration,
        ``reference_count.h:66``)."""
        with self._lock:
            ref = self._entry(oid)
            if ref.owned or ref.borrow_registered:
                return False
            ref.owner_address = owner_address
            ref.borrow_registered = True
            return True

    def add_containment(self, outer: ObjectID, children: list[ObjectID]) -> None:
        """outer's value embeds the children (nested refs): children live at
        least as long as outer does in this process."""
        with self._lock:
            ref = self._entry(outer)
            for child in children:
                if child not in ref.contains:
                    ref.contains.add(child)
                    self._entry(child).contained_in += 1

    # -- counts --------------------------------------------------------------
    def add_local_ref(self, oid: ObjectID) -> None:
        with self._lock:
            self._entry(oid).local += 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        self._dec(oid, "local")

    def add_submitted_ref(self, oid: ObjectID) -> None:
        with self._lock:
            self._entry(oid).submitted += 1

    def remove_submitted_ref(self, oid: ObjectID) -> None:
        self._dec(oid, "submitted")

    def add_borrower(self, oid: ObjectID) -> None:
        with self._lock:
            self._entry(oid).borrowers += 1

    def remove_borrower(self, oid: ObjectID) -> None:
        self._dec(oid, "borrowers")

    def _dec(self, oid: ObjectID, kind: str) -> None:
        freed: list[_Ref] = []
        freed_ids: list[ObjectID] = []
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            setattr(ref, kind, max(0, getattr(ref, kind) - 1))
            self._maybe_free(oid, ref, freed_ids, freed)
        for oid_, ref_ in zip(freed_ids, freed):
            if self._on_object_freed is not None:
                self._on_object_freed(oid_, ref_)

    def _maybe_free(self, oid: ObjectID, ref: _Ref, freed_ids: list, freed: list) -> None:
        if ref.total() > 0:
            return
        self._refs.pop(oid, None)
        freed_ids.append(oid)
        freed.append(ref)
        for child in ref.contains:
            child_ref = self._refs.get(child)
            if child_ref is not None:
                child_ref.contained_in = max(0, child_ref.contained_in - 1)
                self._maybe_free(child, child_ref, freed_ids, freed)

    def drop(self, oid: ObjectID) -> None:
        """Forget an object outright, without invoking the free callback for
        it (the caller already disposed of the value). Used by the owner for
        stream items the consumer never materialized a ref for — their
        ``add_owned_object`` bookkeeping would otherwise persist forever.
        Containment edges are still released (children may free normally)."""
        freed_ids: list[ObjectID] = []
        freed: list[_Ref] = []
        with self._lock:
            ref = self._refs.pop(oid, None)
            if ref is None:
                return
            for child in ref.contains:
                child_ref = self._refs.get(child)
                if child_ref is not None:
                    child_ref.contained_in = max(0, child_ref.contained_in - 1)
                    self._maybe_free(child, child_ref, freed_ids, freed)
        for oid_, ref_ in zip(freed_ids, freed):
            if self._on_object_freed is not None:
                self._on_object_freed(oid_, ref_)

    # -- locations -----------------------------------------------------------
    def add_location(self, oid: ObjectID, node_id: bytes) -> None:
        with self._lock:
            self._entry(oid).locations.add(node_id)

    def remove_location(self, oid: ObjectID, node_id: bytes) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref:
                ref.locations.discard(node_id)

    def get_locations(self, oid: ObjectID) -> set:
        with self._lock:
            ref = self._refs.get(oid)
            return set(ref.locations) if ref else set()

    def has_ref(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._refs

    def num_objects(self) -> int:
        with self._lock:
            return len(self._refs)

    def debug(self, oid: ObjectID) -> dict:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return {}
            return {
                "local": ref.local,
                "submitted": ref.submitted,
                "borrowers": ref.borrowers,
                "contained_in": ref.contained_in,
                "locations": {n.hex() if isinstance(n, bytes) else n for n in ref.locations},
                "owned": ref.owned,
            }
