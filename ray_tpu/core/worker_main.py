"""Worker process entrypoint.

Equivalent of the reference's ``python/ray/_private/workers/default_worker.py``:
parses the raylet-provided arguments, connects the CoreWorker, then parks the
main thread while the io loop serves ``PushTask``.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from .ids import JobID
from .worker import MODE_WORKER, CoreWorker, set_global_worker


def run(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--store-path", required=True)
    parser.add_argument("--store-capacity", type=int, required=True)
    parser.add_argument("--job-id", type=int, default=1)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="[worker %(process)d] %(message)s")
    # SIGUSR1 → dump all thread stacks to the worker log (debugging stuck
    # workers; reference exposes the same via `ray stack`).
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    worker = CoreWorker(
        mode=MODE_WORKER,
        gcs_address=args.gcs_address,
        raylet_address=args.raylet_address,
        node_id=args.node_id,
        store_path=args.store_path,
        store_capacity=args.store_capacity,
        job_id=JobID.from_int(args.job_id),
        worker_id=args.worker_id,
    )
    set_global_worker(worker)
    worker.connect()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    # Orphan watch: workers are direct children of their raylet. If the
    # raylet dies without a graceful stop (driver crash, kill -9), the
    # worker is reparented (PPID changes) — exit instead of idling forever
    # holding memory, sockets, and possibly the TPU tunnel (reference:
    # workers exit on raylet socket close).
    import os as _os

    parent = _os.getppid()
    while not stop.wait(timeout=2.0):
        if _os.getppid() != parent:
            break


def main() -> None:
    run()


if __name__ == "__main__":
    main()
