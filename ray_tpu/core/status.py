"""Exception hierarchy for the runtime.

Equivalent of the reference's ``Status`` codes (``src/ray/common/status.h``)
plus the user-facing exception types in ``python/ray/exceptions.py``.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task.

    Stored as the task's return object; re-raised at ``ray.get`` on the
    caller (reference ``python/ray/exceptions.py`` RayTaskError).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (RayTaskError, (self.function_name, self.traceback_str, self.cause))

    def as_instanceof_cause(self):
        """Return an exception that is also an instance of the cause's type."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if cause_cls is RayTaskError:
            return self
        try:
            class _Wrapped(RayTaskError, cause_cls):  # type: ignore[misc]
                def __init__(self, inner):
                    self._inner = inner

                def __getattr__(self, item):
                    return getattr(self._inner, item)

                def __str__(self):
                    return str(self._inner)

            _Wrapped.__name__ = f"RayTaskError({cause_cls.__name__})"
            _Wrapped.__qualname__ = _Wrapped.__name__
            return _Wrapped(self)
        except TypeError:
            return self


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} died: {reason}")


class ActorUnavailableError(RayTpuError):
    """Actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object was lost and could not be reconstructed from lineage."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost: {reason}")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    """The owner of the object died; its value can never be recovered."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RpcError(RayTpuError):
    """Transport-level RPC failure."""


class PlacementGroupUnschedulableError(RayTpuError):
    pass
