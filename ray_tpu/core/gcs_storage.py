"""GCS table persistence: snapshot file behind the in-memory tables.

Equivalent of the reference's GCS fault-tolerance storage
(``src/ray/gcs/store_client/redis_store_client.h:107``): cluster metadata
(KV, jobs, actors, named actors, placement groups) survives a GCS
restart. Redesign: instead of an external Redis, a local atomic-rename
snapshot (msgpack) flushed by a dirty-flag loop — the GCS is the only
writer, so a WAL buys nothing over cheap whole-table snapshots at this
metadata volume, and there is no external service to operate.
"""

from __future__ import annotations

import os
import tempfile

import msgpack


def pack_tables(tables: dict) -> bytes:
    return msgpack.packb(tables, use_bin_type=True)


def unpack_tables(blob: bytes) -> dict:
    return msgpack.unpackb(blob, raw=False, strict_map_key=False)


class MemoryStorage:
    """Default: nothing persists (reference in-memory GCS store)."""

    persistent = False

    def load(self) -> dict | None:
        return None

    def save_blob(self, blob: bytes) -> None:
        pass

    def close(self) -> None:
        pass


class FileStorage:
    persistent = True

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def load(self) -> dict | None:
        try:
            with open(self.path, "rb") as f:
                return unpack_tables(f.read())
        except (OSError, ValueError):
            return None

    def save_blob(self, blob: bytes) -> None:
        # Atomic rename: a crash mid-write never corrupts the snapshot.
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.path) or ".",
                                   prefix=".gcs_snap_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def close(self) -> None:
        pass


def storage_from_config(session_dir: str):
    from .config import get_config

    cfg = get_config()
    if cfg.gcs_storage_backend == "file":
        return FileStorage(os.path.join(session_dir, "gcs_tables.msgpack"))
    return MemoryStorage()
