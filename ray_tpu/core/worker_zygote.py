"""Worker zygote: fork pre-imported worker processes in milliseconds.

The dominant cost of starting a worker is interpreter boot + the
framework import graph (~0.25 s with a pruned env; multiple seconds when
sitecustomize hooks an accelerator-plugin registration). The zygote pays
that ONCE: the raylet spawns it with a worker environment, it imports
``worker_main`` and then serves fork requests over stdin/stdout — each
new worker is an ``os.fork`` (~ms) of the warm image (the reference's
prestarted-worker pool amortizes the same cost only to its pool depth; a
forkserver amortizes it for every worker).

Zygotes are runtime-env-KEYED: the raylet boots one zygote per env hash,
with that env's variables / PYTHONPATH / working_dir applied to the
zygote process itself — so import-time env vars (JAX_PLATFORMS, plugin
gates) are baked into the forked image exactly as a cold spawn with that
runtime_env would see them. Interpreter-level envs (conda /
py_executable / container) can never fork from a zygote of this
interpreter; the raylet always cold-spawns those.

Safety: the zygote is strictly single-threaded and starts no event loop,
so forking is well-defined; the child applies its per-worker env, detaches
its stdio to the worker log, and runs the normal ``worker_main`` entry.

Protocol (line-delimited JSON):
  zygote -> raylet:  {"ready": true}                 (after imports)
  raylet -> zygote:  {"worker_id": ..., "log": ..., "env": {k: v|null}}
  zygote -> raylet:  {"pid": <child pid>}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--store-path", required=True)
    parser.add_argument("--store-capacity", required=True)
    args = parser.parse_args()

    # Pay the import cost once, pre-fork.
    from . import worker_main  # noqa: F401

    # Children are never waited on here: auto-reap them.
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)

    out = os.fdopen(os.dup(1), "w", buffering=1)
    # The forked children must not inherit a live handle to the protocol
    # pipe (a child crash mid-write would corrupt framing): children close
    # it immediately after fork.
    out.write(json.dumps({"ready": True}) + "\n")

    parent = os.getppid()
    while True:
        line = sys.stdin.readline()
        if not line:
            break  # raylet closed our stdin: shut down (children
            # notice their PPID change and exit themselves)
        if os.getppid() != parent:
            break  # raylet/driver died: orphaned zygote exits
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        pid = os.fork()
        if pid == 0:
            # ---- child: become a normal worker process ----
            try:
                out.close()
                sys.stdin.close()
                for k, v in (req.get("env") or {}).items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = str(v)
                log_fd = os.open(req["log"],
                                 os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                os.dup2(log_fd, 1)
                os.dup2(log_fd, 2)
                os.close(log_fd)
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                worker_main.run([
                    "--raylet-address", args.raylet_address,
                    "--gcs-address", args.gcs_address,
                    "--node-id", args.node_id,
                    "--worker-id", req["worker_id"],
                    "--store-path", args.store_path,
                    "--store-capacity", str(args.store_capacity),
                ])
            except BaseException:
                import traceback

                traceback.print_exc()
            finally:
                os._exit(0)
        out.write(json.dumps({"pid": pid}) + "\n")


if __name__ == "__main__":
    main()
