"""CoreWorker: embedded in every driver and worker process.

Equivalent of the reference's ``CoreWorker`` (``src/ray/core_worker/
core_worker.cc``: SubmitTask:2475, Put:1522, Get:1823, ExecuteTask:3229,
HandlePushTask:3810) plus the transport layer (``transport/
normal_task_submitter.cc``, ``actor_task_submitter.cc``).

Data path:
  * small values   → owner's in-process memory store, shipped inline in RPC
                     replies (reference: <100KB direct-call inlining)
  * large values   → node-local native shm store; other nodes pull chunks
                     via their raylet (ownership-based location lookup)

Round-1 simplifications vs the reference protocol (tracked for round 2):
borrower counts are not reported back to owners (owners pin args only for
the duration of the task), and worker-side ``ray.put`` owns objects at the
worker (as in the reference).
"""

from __future__ import annotations

import functools
import hashlib
import inspect
import logging
import os
import socket
import threading
import time
import traceback
from typing import Any, Callable, Sequence

import cloudpickle

from . import serialization
from .config import get_config
from .generator import ObjectRefGenerator, StreamState
from .ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from .memory_store import MemoryStore
from .object_ref import ObjectRef, install_refcount_hooks
from .refcount import ReferenceCounter
from .rpc import EventLoopThread, RetryableRpcClient, RpcClient, RpcServer
from .status import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayTaskError,
    RayTpuError,
    RpcError,
    TaskCancelledError,
    WorkerCrashedError,
)
from .task_spec import TASK_KIND_ACTOR_CREATION, TASK_KIND_ACTOR_TASK, TASK_KIND_NORMAL, TaskSpec
from ..native.store import ShmClient

logger = logging.getLogger(__name__)

MODE_DRIVER = "driver"
MODE_WORKER = "worker"


class FunctionMissingError(RayTpuError):
    """The GCS has no record of the function (lost export)."""


class FunctionManager:
    """Pickled functions/classes in the GCS KV, keyed by content hash
    (reference ``python/ray/_private/function_manager.py``)."""

    def __init__(self, worker: "CoreWorker"):
        import weakref

        self._worker = worker
        self._exported: set[bytes] = set()
        self._cache: dict[bytes, Any] = {}
        # Submit-hot-path memo: ``export`` must cloudpickle the function on
        # EVERY call just to compute its content hash — 100k no-op submits
        # would pay 100k pickles. Keyed weakly on the live object (a
        # collected function frees its slot, so a recycled id can never
        # alias), one pickle per function definition — the reference's
        # export-once semantics.
        self._memo: "weakref.WeakKeyDictionary[Any, bytes]" = weakref.WeakKeyDictionary()
        self._lock = threading.Lock()

    def export_cached(self, fn: Any, tag: str) -> bytes:
        try:
            fid = self._memo.get(fn)
        except TypeError:  # unhashable/unweakrefable callable
            return self.export((fn, tag))
        if fid is not None:
            return fid
        fid = self.export((fn, tag))
        try:
            self._memo[fn] = fid
        except TypeError:
            pass
        return fid

    def export(self, fn: Any) -> bytes:
        payload = cloudpickle.dumps(fn)
        fid = hashlib.sha1(payload).digest()[:20]
        with self._lock:
            if fid in self._exported:
                return fid
        self._worker._gcs_call("KvPut", {"key": "fn:" + fid.hex(), "value": payload, "overwrite": False})
        with self._lock:
            self._exported.add(fid)
            self._cache[fid] = fn
        return fid

    def get(self, fid: bytes) -> Any:
        with self._lock:
            if fid in self._cache:
                return self._cache[fid]
        reply = self._worker._gcs_call("KvGet", {"key": "fn:" + fid.hex()})
        if not reply.get("found"):
            raise FunctionMissingError(f"Function {fid.hex()} not found in GCS")
        fn = cloudpickle.loads(reply["value"])
        with self._lock:
            self._cache[fid] = fn
        return fn

    def cached(self, fid: bytes):
        with self._lock:
            return self._cache.get(fid)


class TaskManager:
    """Owner-side task table: pending specs, retries, lineage
    (reference ``task_manager.h:212``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict[bytes, dict] = {}
        self._lineage: dict[bytes, TaskSpec] = {}  # return object id -> spec
        self._lineage_bytes = 0
        self._lineage_cost: dict[bytes, int] = {}  # oid -> charged bytes

    @staticmethod
    def _spec_bytes(spec: TaskSpec) -> int:
        """Real lineage footprint of a pinned spec (reference
        task_manager.h:219 caps actual bytes): inline arg payloads
        dominate — a large captured closure must charge what it weighs."""
        total = 256  # fixed fields
        for arg in spec.args:
            total += len(arg.get("blob") or b"") + len(arg.get("meta") or b"") + 64
        return total

    def add_pending(self, spec: TaskSpec, return_ids: list[ObjectID]) -> None:
        with self._lock:
            self._pending[spec.task_id] = {
                "spec": spec,
                "retries_left": spec.max_retries,
                "return_ids": return_ids,
                "submitted_at": time.time(),  # owner-side task span start
            }

    def get_pending(self, task_id: bytes) -> dict | None:
        with self._lock:
            return self._pending.get(task_id)

    def complete(self, task_id: bytes) -> None:
        with self._lock:
            entry = self._pending.pop(task_id, None)
            if entry is not None:
                # Pin lineage so lost objects can be reconstructed
                # (task_manager.h:219 lineage pinning, capped by REAL bytes).
                spec = entry["spec"]
                if spec.max_retries != 0 and self._lineage_bytes < get_config().lineage_max_bytes:
                    cost = self._spec_bytes(spec)
                    for oid in entry["return_ids"]:
                        key = oid.binary()
                        if key in self._lineage:
                            continue  # reconstruction re-completes: no re-charge
                        self._lineage[key] = spec
                        self._lineage_cost[key] = cost
                        self._lineage_bytes += cost

    def consume_retry(self, task_id: bytes) -> bool:
        """Returns True if the task may be retried (decrements budget)."""
        with self._lock:
            entry = self._pending.get(task_id)
            if entry is None:
                return False
            if entry["retries_left"] == 0:
                return False
            if entry["retries_left"] > 0:
                entry["retries_left"] -= 1
            return True

    def fail(self, task_id: bytes) -> dict | None:
        with self._lock:
            return self._pending.pop(task_id, None)

    def lineage_for(self, object_id: ObjectID) -> TaskSpec | None:
        with self._lock:
            return self._lineage.get(object_id.binary())

    def evict_lineage(self, object_id: ObjectID) -> None:
        with self._lock:
            key = object_id.binary()
            if self._lineage.pop(key, None) is not None:
                self._lineage_bytes -= self._lineage_cost.pop(key, 0)

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)


class _ActorState:
    """Client-side view of one actor (ActorTaskSubmitter entry)."""

    def __init__(self, actor_id: bytes):
        self.actor_id = actor_id
        self.address = ""
        self.state = "PENDING_CREATION"
        # Pipelined RegisterActor in flight (unnamed actors): resolution
        # tolerates a GCS "not found" until this lands — a 1k-actor storm
        # must not pay one serial GCS round trip per registration.
        self.register_future = None
        # Set by the actor-channel watcher whenever the GCS publishes a
        # state transition for this actor: resolution parks on it instead
        # of re-polling GetActorInfo on a fixed cadence.
        self.changed = None
        self.seq_no = 0
        # Bumped on each detected death: sequence numbers are scoped to one
        # actor incarnation (the restarted executor expects seq 0).
        self.incarnation = 0
        self.client: RpcClient | None = None
        self.death_cause = ""
        # True only when THIS process created the actor with
        # max_concurrency=1 and no concurrency groups: calls execute
        # strictly serially, so a burst may ride one PushActorTasks RPC
        # without changing overlap semantics. None = unknown (handle
        # received from elsewhere) — never batch those.
        self.serialized: bool | None = None
        self.lock = threading.Lock()


class CoreWorker:
    def __init__(
        self,
        mode: str,
        gcs_address: str,
        raylet_address: str,
        node_id: str,
        store_path: str,
        store_capacity: int,
        job_id: JobID | None = None,
        worker_id: str | None = None,
    ):
        self.mode = mode
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random().hex()
        self.job_id = job_id or JobID.from_int(1)
        self.io = EventLoopThread(f"raytpu-io-{mode}")
        self.gcs_address = gcs_address
        self.gcs = RetryableRpcClient(gcs_address)
        self.raylet = RetryableRpcClient(raylet_address)
        self.raylet_address = raylet_address
        self.memory_store = MemoryStore()
        self.refcounter = ReferenceCounter(on_object_freed=self._on_object_freed)
        self.task_manager = TaskManager()
        self.functions = FunctionManager(self)
        self.shm = ShmClient(store_path, store_capacity) if store_path else None
        self.store_path = store_path

        # Owner-side task submission state.
        self._task_counter = 0
        self._put_counter = 0
        self._counter_lock = threading.Lock()
        if mode == MODE_DRIVER:
            self.current_task_id = TaskID.for_driver_task(self.job_id)
        else:
            self.current_task_id = TaskID.nil()
        self._task_queues: dict[tuple, list] = {}
        # ray_tpu.cancel bookkeeping: cancelled task ids (never retried),
        # dispatched-task -> executing worker address, and (executor side)
        # task -> thread ident for the async-interrupt path.
        self._cancelled_tasks: set[bytes] = set()
        self._dispatched_to: dict[bytes, str] = {}
        # executor side: task -> thread ident (guarded by _exec_lock so a
        # CancelTask async-interrupt can never target a thread that moved
        # on to another task), plus cancels that arrived before execution
        self._exec_threads: dict[bytes, int] = {}
        self._exec_lock = threading.Lock()
        # insertion-ordered dict so the oldest markers (cancels whose
        # task never arrived here) are evicted first once the set is
        # over its size bound — it cannot accumulate forever
        self._cancelled_inbound: dict[bytes, None] = {}
        self._pipelines: dict[tuple, int] = {}
        # Per-shape-key lease-acquisition gate (io-loop only): while one
        # pipeline's multiplexed RequestWorkerLease is in flight, sibling
        # pipelines park here and take grants from its reply instead of
        # issuing their own RPC.
        self._lease_gates: dict[tuple, dict] = {}
        self._spread_salt = 0
        self._queue_lock = threading.Lock()
        self._actors: dict[bytes, _ActorState] = {}
        self._actor_watch_started = False
        # Actor-call submit fast path: specs queue here and the io loop is
        # woken ONCE per burst — run_coroutine_threadsafe's self-pipe
        # write per call is ~0.4 ms of pure syscall, the single biggest
        # cost of a tight actor-call loop before PR 6.
        from collections import deque as _deque

        self._actor_submit_q: "_deque" = _deque()
        self._actor_submit_active = False
        self._actor_submit_lock = threading.Lock()
        self._node_table: dict[str, dict] = {}
        # Actor-handle GC: non-detached, unnamed actors die when the last
        # handle in the owning process is dropped (reference actor.py
        # __ray_terminate__ on handle GC).
        self._actor_handle_counts: dict[bytes, int] = {}
        self._owned_actors: set[bytes] = set()
        # Borrowing protocol state: per-owner ordered RPC clients, and
        # temporary holds on owned objects we returned to a caller that has
        # not yet registered as a borrower (expiring failsafe).
        self._borrow_clients: dict[str, RetryableRpcClient] = {}
        self._borrow_clients_lock = threading.Lock()
        self._borrow_holds: dict[bytes, list[float]] = {}
        self._borrow_holds_lock = threading.Lock()
        # Owner-side streaming-generator state, keyed by task id
        # (reference task_manager.h:212 ObjectRefStream map).
        self._streams: dict[bytes, StreamState] = {}
        # Driver-side view of the GCS error-info channel (diagnostics):
        # most recent ErrorEvents seen by the auto-subscriber.
        from collections import deque

        self._recent_errors: deque = deque(maxlen=256)

        # Executor-side state (worker mode).
        self.actor_instance: Any = None
        self.actor_id: bytes = b""
        # Task pushes received over this worker's lifetime: the raylet's
        # orphan-lease watchdog probes it (LeaseProbe) before reclaiming a
        # lease whose AckLease never arrived.
        self._pushes_total = 0
        # Per-caller sequencing (reference: per-handle sequence numbers,
        # actor_task_submitter.cc; callers are identified by owner address).
        self._actor_next_seq: dict[str, int] = {}
        self._actor_ooo_buffer: dict[tuple[str, int], Any] = {}
        self._actor_sem: threading.Semaphore | None = None
        self._actor_max_concurrency = 1
        self._actor_group_sems: dict[str, threading.Semaphore] = {}
        self._exec_local = threading.local()

        # Task execution threads: the loop's default executor caps at
        # cpu_count+4 which starves long-poll-style actor methods (Serve
        # listen_for_change); give every worker a deep pool.
        from concurrent.futures import ThreadPoolExecutor

        self.io.loop.set_default_executor(ThreadPoolExecutor(
            max_workers=get_config().worker_executor_threads,
            thread_name_prefix="raytpu-exec"))

        # RPC server for owner + executor duties. Bind to the node's
        # routable interface (the host our raylet registered with the GCS)
        # so the advertised worker address — and everything derived from
        # it, e.g. cross-node DAG channel servers — is reachable from
        # other hosts, not just loopback.
        node_host = self.raylet_address.rpartition(":")[0]
        if node_host in ("", "localhost"):
            node_host = "127.0.0.1"
        self.server = RpcServer(node_host, 0)
        self.server.register_service(self)
        # Task-event buffer: status timestamps flushed to the GCS on an
        # interval (task_event_buffer.h:224; powers list_tasks + timeline).
        from .task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(self.worker_id, self.node_id)

        self.io.run_sync(self.server.start())
        self.address = self.server.address
        self.io.run_coro(self._borrow_hold_sweeper())
        self.io.run_coro(self._task_event_flusher())
        self.io.run_coro(self._global_gc_poller())

        install_refcount_hooks(self._hook_add_local, self._hook_remove_local)

    # ------------------------------------------------------------- lifecycle
    def connect(self) -> None:
        self._raylet_call(
            "RegisterWorker",
            {
                "worker_id": self.worker_id,
                "address": self.address,
                "pid": os.getpid(),
                "is_driver": self.mode == MODE_DRIVER,
            },
        )
        if self.mode == MODE_DRIVER and get_config().log_to_driver:
            self.io.run_coro(self._stream_logs_to_driver())
        if self.mode == MODE_DRIVER:
            # Auto-subscribe to the error-info channel: worker/raylet/serve
            # failures surface in the driver's log, not just worker files
            # (reference: listen_error_messages in worker.py).
            self.io.run_coro(self._error_info_poller())

    async def _error_info_poller(self) -> None:
        """Driver-side error-info subscriber: long-poll the GCS channel,
        cache events for inspection, and log each one — a replica or
        remote-worker failure becomes visible at the driver without
        grepping per-worker log files."""
        import asyncio

        from ..diagnostics.errors import ERROR_INFO_CHANNEL

        cursor = None  # start at the current end: no history replay
        while True:
            try:
                if cursor is None:
                    reply = await self.gcs.call("ListErrors", {"limit": 0}, timeout=10.0)
                    cursor = reply.get("cursor", 0)
                reply = await self.gcs.call(
                    "SubscribePoll",
                    {"cursors": {ERROR_INFO_CHANNEL: cursor}, "timeout": 30.0},
                    timeout=45.0,
                )
            except Exception:
                await asyncio.sleep(1.0)
                continue
            msgs = (reply.get("messages") or {}).get(ERROR_INFO_CHANNEL, [])
            if not msgs:
                # Empty long-poll: re-check the channel cursor — a restarted
                # GCS resets Publisher sequences, and a cursor PAST the new
                # end would filter every future event forever (same clamp as
                # PollGlobalGc).
                try:
                    base = await self.gcs.call("ListErrors", {"limit": 0}, timeout=10.0)
                    cursor = min(cursor, base.get("cursor", cursor))
                except Exception:
                    pass
                continue
            for seq, event in msgs:
                cursor = max(cursor, seq)
                self._recent_errors.append(event)
                logger.warning(
                    "ErrorEvent [%s/%s] node=%s: %s",
                    event.get("source", "?"), event.get("type", "?"),
                    (event.get("node_id") or "")[:8], event.get("message", ""))

    async def _stream_logs_to_driver(self) -> None:
        """Long-poll the GCS log channel and echo worker output with a
        ``(worker=..., node=...)`` prefix (reference: driver-side
        print_logs over the log pubsub)."""
        import asyncio
        import sys

        cursor = None  # None = "start at the current end" (no history replay)
        while True:
            try:
                reply = await self.gcs.call(
                    "PollLogs", {"cursor": cursor, "timeout": 10.0}, timeout=20.0
                )
            except Exception:
                await asyncio.sleep(1.0)
                continue
            cursor = reply.get("cursor", cursor)
            for msg in reply.get("messages", []):
                node = msg["node_id"][:8]
                for entry in msg["batch"]:
                    prefix = f"({entry['worker'][:8]}, node={node}) "
                    for line in entry["lines"]:
                        print(prefix + line, file=sys.stderr)

    def shutdown(self) -> None:
        install_refcount_hooks(lambda r: None, lambda r: None)
        # final event flush so short-lived drivers/workers leave a trace
        try:
            events, dropped = self.task_events.drain()
            if events or dropped:
                self._gcs_call("AddTaskEvents", {"events": events, "dropped": dropped}, timeout=5.0)
        except Exception:
            pass
        # Flush read-ref pins in one call BEFORE stopping the io loop:
        # per-object PlasmaRelease from GC'd buffers would race teardown
        # and leak pins on the raylet (objects become unspillable).
        try:
            self._raylet_call("ReleaseReader", {"reader": self.worker_id}, timeout=5.0)
        except Exception:
            pass

        async def _close_all():
            await self.server.stop()
            for state in self._actors.values():
                if state.client is not None:
                    await state.client.close()
            for client in self._borrow_clients.values():
                await client.close()
            await self.gcs.close()
            await self.raylet.close()

        try:
            self.io.run_sync(_close_all(), timeout=5)
        except Exception:
            pass
        self.io.stop()
        if self.shm:
            self.shm.close()

    def _gcs_call(self, method: str, payload: dict, timeout: float | None = 30.0) -> dict:
        return self.io.run_sync(self.gcs.call(method, payload, timeout))

    def _raylet_call(self, method: str, payload: dict, timeout: float | None = 30.0) -> dict:
        return self.io.run_sync(self.raylet.call(method, payload, timeout))

    def pin_loop_worker(self, actor_id: str, pinned: bool,
                        node_id: str | None = None) -> bool:
        """Tell the raylet hosting ``actor_id`` that its worker parks a
        resident compiled-loop executor (``dag/loop.py``): pinned leases
        are exempt from the orphan-lease watchdog's reclaim (a parked
        loop looks exactly like a stranded grant — no pushes, no
        finished task — and reclaiming it would kill a live pipeline)."""
        async def _go() -> bool:
            addr = (await self._raylet_address_for(node_id)
                    if node_id else self.raylet_address)
            if addr is None:
                return False
            client = RpcClient(addr)
            try:
                reply = await client.call(
                    "PinLoopWorker",
                    {"actor_id": actor_id, "pinned": bool(pinned)},
                    timeout=10.0)
                return bool(reply.get("ok"))
            finally:
                await client.close()

        try:
            return self.io.run_sync(_go())
        except Exception:
            return False  # pinning is protective, never fatal

    # -------------------------------------------------------------- refcount
    def _hook_add_local(self, ref: ObjectRef) -> None:
        oid = ref.id()
        self.refcounter.add_local_ref(oid)
        self.refcounter.set_callsite(oid, ref.callsite)
        owner = ref.owner_address
        if owner and owner != self.address and self.refcounter.note_borrowed(oid, owner):
            # First local ref to a borrowed object: register with its owner
            # so the owner keeps it alive (reference_count.h:66 borrowing).
            self.io.run_coro(self._send_borrow(owner, "AddBorrower", oid))

    def _hook_remove_local(self, ref: ObjectRef) -> None:
        self.refcounter.remove_local_ref(ref.id())

    def _owner_client(self, owner_address: str) -> RetryableRpcClient:
        """One ordered connection per owner (shared by the borrowing
        protocol and generator-item reports, so neither can race)."""
        with self._borrow_clients_lock:
            client = self._borrow_clients.get(owner_address)
            if client is None:
                client = self._borrow_clients[owner_address] = RetryableRpcClient(owner_address)
            return client

    async def _send_borrow(self, owner_address: str, method: str, oid: ObjectID) -> None:
        try:
            client = self._owner_client(owner_address)
            await client.call(method, {"id": oid.binary(), "borrower": self.worker_id}, timeout=30.0)
        except Exception:
            pass  # owner died: its state is gone anyway

    def _on_object_freed(self, oid: ObjectID, ref) -> None:
        """All references dropped. Owned objects: delete every copy
        (reference_count.cc → plasma Delete broadcast). Borrowed objects:
        report the release back to the owner."""
        if not ref.owned:
            if ref.borrow_registered and ref.owner_address:
                self.io.run_coro(self._send_borrow(ref.owner_address, "RemoveBorrower", oid))
            return
        self.memory_store.delete(oid)
        self.task_manager.evict_lineage(oid)
        locations = set(ref.locations)

        async def _free():
            for node_id in locations:
                addr = await self._raylet_address_for(node_id)
                if addr is None:
                    continue
                try:
                    client = RpcClient(addr)
                    await client.call("PlasmaDelete", {"id": oid.binary()}, timeout=5.0)
                    await client.close()
                except Exception:
                    pass

        if locations:
            self.io.run_coro(_free())

    async def _raylet_address_for(self, node_id) -> str | None:
        node_hex = node_id if isinstance(node_id, str) else node_id.hex()
        if node_hex == self.node_id:
            return self.raylet_address
        node = self._node_table.get(node_hex)
        if node is None:
            reply = await self.gcs.call("GetAllNodes", {}, timeout=10.0)
            self._node_table = {n["node_id"]: n for n in reply["nodes"]}
            node = self._node_table.get(node_hex)
        return node["address"] if node else None

    # ------------------------------------------------------------------- put
    def put(self, value: Any, *, _owner_ref: ObjectRef | None = None) -> ObjectRef:
        with self._counter_lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_counter)
        s = serialization.serialize_value(value)
        self._store_owned_value(oid, s.metadata, s, s.contained)
        return ObjectRef(oid, self.address)

    def _store_owned_value(self, oid: ObjectID, metadata: bytes, blob, contained: list) -> None:
        cfg = get_config()
        contained_ids = [r.id() for r in contained]
        self.refcounter.add_owned_object(oid, contained_ids)
        nbytes = blob.nbytes if isinstance(blob, serialization.Serialized) else len(blob)
        self.refcounter.set_size(oid, nbytes)
        if nbytes <= cfg.max_inline_object_size:
            if isinstance(blob, serialization.Serialized):
                blob = blob.to_blob()
            self.memory_store.put(oid, metadata, blob)
        else:
            self._plasma_put(oid, metadata, blob)
            self.memory_store.put_plasma_marker(oid, self.node_id.encode())
            self.refcounter.add_location(oid, self.node_id)

    def _plasma_put(self, oid: ObjectID, metadata: bytes, blob) -> None:
        """``blob`` may be bytes OR a ``serialization.Serialized`` — the
        latter frames its buffers DIRECTLY into the mmapped arena (the
        plasma-client zero-copy create path, reference ``plasma/store.h``
        client mmap + ``fling.cc`` fd passing): one copy end to end
        instead of pickle-concat + frame + mmap write."""
        parts = isinstance(blob, serialization.Serialized)
        data_size = blob.nbytes if parts else len(blob)
        reply = self._raylet_call(
            "PlasmaCreate",
            {"id": oid.binary(), "data_size": data_size, "meta_size": len(metadata),
             "creator": self.worker_id},
        )
        if reply.get("exists"):
            return  # already sealed (e.g. a retried task's deterministic return)
        if reply.get("error"):
            from .status import ObjectStoreFullError

            raise ObjectStoreFullError(reply.get("detail", "object store full"))
        offset = reply["offset"]
        if parts:
            blob.write_into(self.shm.read(offset, data_size))
        else:
            self.shm.write(offset, blob)
        if metadata:
            self.shm.write(offset + data_size, metadata)
        self._raylet_call("PlasmaSeal", {"id": oid.binary()})

    # ------------------------------------------------------------------- get
    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None) -> list:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(ref, deadline) for ref in refs]

    def _remaining(self, deadline: float | None) -> float | None:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _get_one(self, ref: ObjectRef, deadline: float | None,
                 pull_class: str = "get"):
        oid = ref.id()
        owned = self.refcounter.is_owned(oid)
        while True:
            entry = self.memory_store.get_if_exists(oid)
            if entry is not None and not entry.in_plasma:
                return self._deserialize(entry.metadata, entry.blob, oid)
            if entry is not None and entry.in_plasma:
                return self._get_from_plasma(ref, deadline, pull_class)
            if owned:
                remaining = self._remaining(deadline)
                ready, _ = self.memory_store.wait_ready([oid], 1, remaining)
                if not ready:
                    raise GetTimeoutError(f"Timed out getting {oid.hex()}")
                continue
            # Borrowed ref: ask the owner.
            status = self._owner_status(ref, deadline)
            if status.get("inline"):
                return self._deserialize(status["metadata"], status["blob"], oid)
            if status.get("in_plasma"):
                return self._get_from_plasma(ref, deadline, pull_class)
            raise ObjectLostError(oid, status.get("error", "owner could not locate object"))

    def _owner_status(self, ref: ObjectRef, deadline: float | None) -> dict:
        remaining = self._remaining(deadline)
        try:
            owner = RpcClient(ref.owner_address)

            async def _call():
                try:
                    return await owner.call(
                        "GetObjectStatus",
                        {"id": ref.binary(), "wait": True, "timeout": remaining if remaining is not None else 3600.0},
                        timeout=None if remaining is None else remaining + 5.0,
                    )
                finally:
                    await owner.close()

            reply = self.io.run_sync(_call())
            return reply
        except RpcError as e:
            from .status import OwnerDiedError

            raise OwnerDiedError(ref.id(), f"owner {ref.owner_address} unreachable: {e}")

    # Per-attempt PlasmaGetInfo wait: a lost object must surface as
    # not-found well before the caller's deadline, or lineage
    # reconstruction never gets time to run (the raylet used to long-poll
    # the ENTIRE get() budget before admitting the object was gone).
    _PLASMA_PROBE_S = 5.0

    def _get_from_plasma(self, ref: ObjectRef, deadline: float | None,
                         pull_class: str = "get"):
        oid = ref.id()
        t0 = time.monotonic()  # no-deadline gets still give up after 1 h
        while True:
            remaining = self._remaining(deadline)
            probe = (self._PLASMA_PROBE_S if remaining is None
                     else max(0.0, min(remaining, self._PLASMA_PROBE_S)))
            reply = self._raylet_call(
                "PlasmaGetInfo",
                {
                    "id": oid.binary(),
                    "owner_address": ref.owner_address or self.address,
                    "timeout": probe,
                    # The raylet holds a store ref for us until we release, so
                    # the object can't be spilled/evicted while views are alive.
                    "pin_read": True,
                    "reader": self.worker_id,
                    # Pull admission class (raylet orders get > wait > task_arg).
                    "pull_class": pull_class,
                },
                timeout=probe + 10.0,
            )
            if reply.get("found"):
                break
            # Lost from every reachable node: try lineage reconstruction
            # (object_recovery_manager.h:90,106), then keep probing — a
            # copy may still appear (in-flight push, restarting holder)
            # until the caller's deadline truly expires.
            if self._try_reconstruct(oid, deadline):
                continue
            remaining = self._remaining(deadline)
            if (remaining is not None and remaining <= 0) or (
                    remaining is None and time.monotonic() - t0 > 3600.0):
                raise ObjectLostError(
                    oid, "not found on any node and not reconstructable")
        data = self.shm.read(reply["offset"], reply["data_size"])
        meta = bytes(self.shm.read(reply["offset"] + reply["data_size"], reply["meta_size"]))
        # Zero-copy deserialization aliases the arena; release the read ref
        # only when the last derived view (e.g. a reconstructed numpy array)
        # is GC'd, never before (plasma Buffer lifetime semantics).
        buf = serialization.PlasmaBuffer(data, self._make_read_releaser(oid))
        del data
        return self._deserialize(meta, buf, oid)

    def _make_read_releaser(self, oid: ObjectID):
        binary = oid.binary()
        reader = self.worker_id
        io, raylet = self.io, self.raylet

        def _release():
            coro = raylet.call("PlasmaRelease", {"id": binary, "reader": reader}, 10.0)
            try:
                io.run_coro(coro)
            except Exception:
                # Shutdown: the raylet reaps reader refs with the worker.
                # Close the never-scheduled coroutine so teardown doesn't
                # warn "coroutine was never awaited".
                coro.close()

        return _release

    def _try_reconstruct(self, oid: ObjectID, deadline: float | None) -> bool:
        spec = self.task_manager.lineage_for(oid)
        if spec is None:
            return False
        logger.warning("Reconstructing %s by resubmitting task %s", oid.hex()[:12], spec.name)
        return_ids = [ObjectID.for_task_return(TaskID(spec.task_id), i + 1) for i in range(spec.num_returns)]
        for rid in return_ids:
            self.memory_store.delete(rid)
        self.task_manager.add_pending(spec, return_ids)
        self._enqueue_task(spec)
        remaining = self._remaining(deadline)
        ready, _ = self.memory_store.wait_ready([oid], 1, remaining if remaining is not None else 300.0)
        return bool(ready)

    def _deserialize(self, metadata: bytes, blob, oid: ObjectID):
        value = serialization.deserialize(metadata, blob)
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        return value

    # ------------------------------------------------------------------ wait
    def wait(self, refs: Sequence[ObjectRef], num_returns: int, timeout: float | None):
        """Event-driven wait (reference ``core_worker.cc`` Wait): one asyncio
        waiter per ref resolves on memory-store arrival (owned refs) or on an
        owner long-poll (borrowed refs) — no polling loop."""
        refs = list(refs)
        fut = self.io.run_coro(self._wait_async(refs, num_returns, timeout))
        ready_idx = fut.result()
        ready = [refs[i] for i in sorted(ready_idx)][:num_returns]
        not_ready = [r for r in refs if r not in ready]
        return ready, not_ready

    async def _wait_async(self, refs: list[ObjectRef], num_returns: int, timeout: float | None) -> list[int]:
        import asyncio

        loop = asyncio.get_running_loop()
        ready: list[int] = []
        pending: dict[asyncio.Task, int] = {}
        cleanups = []
        for i, ref in enumerate(refs):
            if self.memory_store.contains(ref.id()):
                ready.append(i)
            elif self.refcounter.is_owned(ref.id()) or not ref.owner_address or ref.owner_address == self.address:
                fut: asyncio.Future = loop.create_future()

                def _on_ready(_oid, fut=fut):
                    loop.call_soon_threadsafe(lambda: fut.done() or fut.set_result(True))

                if self.memory_store.add_callback(ref.id(), _on_ready):
                    cleanups.append((ref.id(), _on_ready))
                    pending[asyncio.ensure_future(self._await_future(fut))] = i
                else:
                    ready.append(i)
            else:
                pending[asyncio.ensure_future(self._wait_borrowed(ref, timeout))] = i
        try:
            deadline = None if timeout is None else time.monotonic() + timeout
            while len(ready) < num_returns and pending:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    pending.keys(), timeout=remaining, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break  # timeout
                for task in done:
                    ready.append(pending.pop(task))
            return ready
        finally:
            for task in pending:
                task.cancel()
            for oid, cb in cleanups:
                self.memory_store.remove_callback(oid, cb)

    @staticmethod
    async def _await_future(fut) -> None:
        await fut

    async def _wait_borrowed(self, ref: ObjectRef, timeout: float | None) -> None:
        """Long-poll the owner until a borrowed ref is ready. Owner death
        counts as ready (the subsequent get raises OwnerDiedError)."""
        owner = RpcClient(ref.owner_address)
        try:
            while True:
                try:
                    status = await owner.call(
                        "GetObjectStatus",
                        {"id": ref.binary(), "wait": True, "timeout": 30.0 if timeout is None else min(timeout, 3600.0)},
                        timeout=None,
                    )
                except RpcError:
                    return
                if status.get("inline") or status.get("in_plasma"):
                    return
        finally:
            await owner.close()

    # --------------------------------------------------------- task submission
    def next_task_id(self) -> TaskID:
        with self._counter_lock:
            self._task_counter += 1
            return TaskID.for_normal_task(self.job_id, self.current_task_id, self._task_counter)

    def _attach_trace(self, spec: TaskSpec) -> None:
        """Give the spec a trace context: continue the submitting thread's
        active trace (the task's span becomes a child of it) or root a
        fresh one, so every task is traceable end to end."""
        from ..observability import tracing

        if not get_config().enable_tracing:
            return
        ctx = tracing.current()
        if ctx is None:
            spec.trace_id = tracing.new_trace_id()
        else:
            spec.trace_id = ctx.trace_id
            spec.parent_span_id = ctx.span_id
        spec.span_id = tracing.new_span_id()

    def _record_submit(self, spec: TaskSpec) -> None:
        extra = {"trace_id": spec.trace_id} if spec.trace_id else None
        self.task_events.record(spec.task_id, spec.name, "SUBMITTED",
                                kind=spec.kind, extra=extra)

    def _record_task_span(self, spec: TaskSpec, status: str) -> None:
        """Owner-side umbrella span for one task: submit → settled."""
        if not spec.trace_id:
            return
        from ..observability import tracing

        entry = self.task_manager.get_pending(spec.task_id)
        start = (entry or {}).get("submitted_at") or time.time()
        tracing.record_span(tracing.make_span(
            f"task {spec.name}", "task", start, time.time(), spec.trace_id,
            spec.parent_span_id, spec.span_id,
            attrs={"task_id": spec.task_id.hex(), "status": status}))

    @staticmethod
    def _accelerator_runtime_env(resources: dict | None, runtime_env: dict | None) -> dict:
        """Workers are pinned to JAX_PLATFORMS=cpu by the raylet unless the
        runtime_env explicitly overrides it. A task/actor that REQUESTS the
        TPU obviously wants the accelerator: inject the opt-out so users
        don't silently train/infer on CPU while holding a TPU lease."""
        if not resources or not resources.get("TPU"):
            return runtime_env or {}
        renv = dict(runtime_env or {})
        env_vars = dict(renv.get("env_vars") or {})
        if "JAX_PLATFORMS" not in env_vars:
            env_vars["JAX_PLATFORMS"] = None  # unset -> platform autodetect
            renv["env_vars"] = env_vars
        return renv

    def submit_task(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        num_returns: int | str = 1,
        resources: dict | None = None,
        max_retries: int | None = None,
        scheduling_strategy: dict | None = None,
        placement_group_id: bytes = b"",
        placement_group_bundle_index: int = -1,
        runtime_env: dict | None = None,
        generator_backpressure: int = 0,
    ) -> list[ObjectRef] | ObjectRefGenerator:
        cfg = get_config()
        streaming = num_returns == "streaming"
        n_returns = -1 if streaming else num_returns
        fid = self.functions.export_cached(fn, "task")
        task_id = self.next_task_id()
        spec = TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=name or getattr(fn, "__name__", "task"),
            function_id=fid,
            kind=TASK_KIND_NORMAL,
            args=self._serialize_args(args, kwargs),
            num_returns=n_returns,
            generator_backpressure=generator_backpressure,
            resources=resources or {},
            max_retries=cfg.task_max_retries if max_retries is None else max_retries,
            owner_address=self.address,
            parent_task_id=self.current_task_id.binary(),
            scheduling_strategy=scheduling_strategy or {},
            placement_group_id=placement_group_id,
            placement_group_bundle_index=placement_group_bundle_index,
            runtime_env=self._accelerator_runtime_env(resources, runtime_env),
        )
        self._attach_trace(spec)
        if streaming:
            return self._submit_streaming(spec)
        return_ids = [ObjectID.for_task_return(task_id, i + 1) for i in range(num_returns)]
        for rid in return_ids:
            self.refcounter.add_owned_object(rid)
        self.task_manager.add_pending(spec, return_ids)
        self._record_submit(spec)
        self._enqueue_task(spec)
        return [ObjectRef(rid, self.address) for rid in return_ids]

    def _submit_streaming(self, spec: TaskSpec) -> ObjectRefGenerator:
        stream = StreamState(spec.task_id)
        self._streams[spec.task_id] = stream
        self.task_manager.add_pending(spec, [])
        self._record_submit(spec)
        if spec.kind == TASK_KIND_ACTOR_TASK:
            self.io.run_coro(self._submit_actor_task_async(spec))
        else:
            self._enqueue_task(spec)
        return ObjectRefGenerator(self, stream, self.address)

    def _serialize_args(self, args: tuple, kwargs: dict) -> list:
        cfg = get_config()
        wire_args = []
        for kind, item in [("a", a) for a in args] + [("k", (k, v)) for k, v in kwargs.items()]:
            key = None
            if kind == "k":
                key, item = item
            if isinstance(item, ObjectRef):
                self.refcounter.add_submitted_ref(item.id())
                entry = {"t": "r", "id": item.binary(), "owner": item.owner_address or self.address}
            else:
                metadata, blob, contained = serialization.serialize(item)
                if len(blob) <= cfg.max_inline_object_size and not contained:
                    entry = {"t": "v", "meta": metadata, "blob": blob}
                else:
                    # Promote large inline args to owned objects; the
                    # submitted-ref count keeps them alive until completion.
                    ref = self.put(item)
                    self.refcounter.add_submitted_ref(ref.id())
                    entry = {"t": "r", "id": ref.binary(), "owner": self.address}
            if key is not None:
                entry["key"] = key
            wire_args.append(entry)
        return wire_args

    def _release_submitted_refs(self, spec: TaskSpec) -> None:
        for arg in spec.args:
            if arg.get("t") == "r":
                self.refcounter.remove_submitted_ref(ObjectID(arg["id"]))

    def _shape_key(self, spec: TaskSpec) -> tuple:
        strategy = spec.scheduling_strategy or {}
        # Spread tasks get one lease each (salted key): sharing a lease
        # pipeline would pack them all onto the first leased worker.
        salt = 0
        if strategy.get("type") == "spread":
            with self._counter_lock:
                self._spread_salt += 1
                salt = self._spread_salt
        # The FULL runtime env keys the pipeline: leases hold workers built
        # for one env, and a task with different py_modules/pip/working_dir
        # pushed onto a reused lease would import the wrong world.
        renv = spec.runtime_env or {}
        renv_key = ""
        if renv:
            import json

            renv_key = json.dumps(renv, sort_keys=True, default=str)
            if renv.get("py_modules"):
                # Content digest, not just paths: an edited module must key
                # a fresh pipeline, or a warm lease (idle-grace reuse)
                # would push the task onto a worker with the stale code.
                from .runtime_env import _hash_paths

                renv_key += ":" + _hash_paths(list(renv["py_modules"]))
        return (
            tuple(sorted(spec.required_resources().items())),
            spec.placement_group_id,
            spec.placement_group_bundle_index,
            tuple(sorted(strategy.items())) if strategy else (),
            renv_key,
            # Retriable and non-retriable tasks never share a lease: the
            # raylet's OOM policy kills leases whose probe spec was
            # retriable, which must hold for every task pushed on them.
            bool(spec.max_retries),
            salt,
        )

    def cancel(self, ref, *, force: bool = False) -> None:
        """Cancel the task producing ``ref`` (reference ``ray.cancel``,
        ``_private/worker.py:3086``). Queued tasks are dropped; a RUNNING
        task gets TaskCancelledError raised asynchronously in its executor
        thread (takes effect at the next Python bytecode — a task blocked
        in a C call is only reachable with ``force``); ``force=True``
        kills the executing worker process. Cancelled tasks never retry;
        already-finished tasks are untouched (best-effort semantics)."""
        oid = ref.id() if hasattr(ref, "id") else ref
        task_id = oid.task_id().binary()
        if oid.is_put():
            raise ValueError("ray_tpu.cancel only applies to task returns, "
                             "not ray_tpu.put objects")
        if self.task_manager.get_pending(task_id) is None:
            return  # already finished (or never ours): best-effort no-op,
                    # and no marker left behind to leak
        self._cancelled_tasks.add(task_id)
        # queued (pre-dispatch): drop + fail in place
        with self._queue_lock:
            dropped = None
            for key, queue in self._task_queues.items():
                for spec in queue:
                    if spec.task_id == task_id:
                        dropped = spec
                        queue.remove(spec)
                        break
                if dropped is not None:
                    break
        if dropped is not None:
            self._fail_task(dropped, TaskCancelledError(task_id.hex()[:12]))
            return
        # dispatched: interrupt (or kill) the executing worker
        addr = self._dispatched_to.get(task_id)
        if addr is None:
            return  # finished, unknown, or actor task — no-op
        async def _send():
            client = RpcClient(addr)
            try:
                await client.call("CancelTask",
                                  {"task_id": task_id, "force": force},
                                  timeout=10.0)
            except Exception as e:
                logger.debug("CancelTask to %s failed: %s", addr, e)
            finally:
                await client.close()
        self.io.run_coro(_send())

    def _enqueue_task(self, spec: TaskSpec) -> None:
        if spec.task_id in self._cancelled_tasks:
            # cancelled tasks never (re)enter the queue — a retry after a
            # force-kill must fail, not resubmit
            self._fail_task(spec, TaskCancelledError(spec.task_id.hex()[:12]))
            return
        key = self._shape_key(spec)
        with self._queue_lock:
            self._task_queues.setdefault(key, []).append(spec)
            active = self._pipelines.get(key, 0)
            queued = len(self._task_queues[key])
            cfg = get_config()
            if active < min(queued, cfg.max_pending_lease_requests_per_scheduling_category):
                self._pipelines[key] = active + 1
                self.io.run_coro(self._lease_pipeline(key))

    def _lease_want(self, key: tuple, extra_waiters: int) -> int:
        """How many workers one RequestWorkerLease should ask for: enough
        for the pipelines parked on this key plus the queue's depth, up to
        ``lease_grant_batch_size``. Spread keys are salted per task (one
        spec per key) — never multiplex those."""
        cap = get_config().lease_grant_batch_size
        if cap <= 1 or key[-1]:
            return 1
        with self._queue_lock:
            queued = len(self._task_queues.get(key) or ())
        return max(1, min(cap, max(1 + extra_waiters, queued)))

    # How long a pipeline parks on a sibling's in-flight lease RPC before
    # de-coalescing and issuing its own: config lease_coalesce_degrade_ms.
    # Fast-path replies land in milliseconds, so coalescing keeps its win
    # there; a leader stuck on a dropped reply or a slow worker spawn must
    # NOT hold every other pipeline hostage for its full RPC timeout —
    # under faults the owner degrades to the old one-RPC-per-pipeline
    # concurrency. The deadline runs on the chaos clock, so a VirtualClock
    # chaos replay fires the degrade deterministically (frozen clock =
    # never; an explicit advance() = exactly then).

    @staticmethod
    async def _await_gate_with_degrade(fut: "asyncio.Future"):
        """Await a lease-gate future up to the coalesce-degrade window,
        measured on the chaos clock. Raises asyncio.TimeoutError when the
        window elapses (virtual or wall) before the leader resolves."""
        import asyncio

        from ..chaos import clock as chaos_clock

        wait_s = get_config().lease_coalesce_degrade_ms / 1000.0
        clk = chaos_clock.get_clock()
        deadline = clk.now() + wait_s
        # Wall clock: one wait_for covers the window. Virtual clock:
        # poll in small real slices so explicit advance() calls (and
        # rate-scaled time) are observed without wall-time coupling.
        slice_s = wait_s if isinstance(clk, chaos_clock.WallClock) else 0.02
        while True:
            remaining = deadline - clk.now()
            if remaining <= 0:
                raise asyncio.TimeoutError
            try:
                return await asyncio.wait_for(
                    asyncio.shield(fut), min(slice_s, max(remaining, 0.001))
                    if slice_s != wait_s else remaining)
            except asyncio.TimeoutError:
                continue

    async def _acquire_lease_shared(self, key: tuple, spec: TaskSpec):
        """Coalesce same-shape lease acquisition across this owner's
        pipelines: one leader RPC requests workers for everyone parked on
        the key; followers receive grants from the leader's reply instead
        of each paying ``_acquire_lease``'s serial round trip. Returns
        ``(leases, reason)`` like ``_acquire_lease`` — the caller owns
        every returned lease (extras beyond the first come from
        multiplexed grants the waiters didn't absorb)."""
        import asyncio

        if get_config().lease_grant_batch_size <= 1 or key[-1]:
            # Multiplexing off (or a salted spread key, one spec per key):
            # the legacy fully-concurrent one-RPC-per-pipeline protocol.
            return await self._acquire_lease(spec)
        while True:
            gate = self._lease_gates.get(key)
            if gate is not None:
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                gate["waiters"].append(fut)
                try:
                    outcome, value = await self._await_gate_with_degrade(fut)
                except asyncio.TimeoutError:
                    if fut in gate["waiters"]:
                        gate["waiters"].remove(fut)
                    if fut.done():  # resolved in the race window
                        outcome, value = fut.result()
                    else:
                        fut.cancel()
                        return await self._acquire_lease(
                            spec, num_workers=self._lease_want(key, 0))
                if outcome == "lease":
                    return [value], ""
                if outcome == "denied":
                    return None, value
                continue  # grants ran out before our turn: try again
            gate = {"waiters": []}
            self._lease_gates[key] = gate
            try:
                leases, reason = await self._acquire_lease(
                    spec, num_workers=self._lease_want(key, 0))
            finally:
                self._lease_gates.pop(key, None)
            waiters = gate["waiters"]
            if leases is None:
                for f in waiters:
                    if not f.done():
                        f.set_result(("denied", reason))
                return None, reason
            keep, extras = [leases[0]], leases[1:]
            for f in waiters:
                if f.done():
                    continue
                if extras:
                    f.set_result(("lease", extras.pop(0)))
                else:
                    f.set_result(("retry", None))
            keep.extend(extras)
            return keep, ""

    async def _return_lease(self, lease) -> None:
        """Give an unused multiplexed grant back to its raylet."""
        _addr, worker_id, client, owns = lease
        try:
            await client.call("ReturnWorker", {"worker_id": worker_id},
                              timeout=10.0)
        except Exception:
            pass
        if owns:
            await client.close()

    async def _lease_pipeline(self, key: tuple, preacquired=None) -> None:
        """One lease worker: acquire a lease, drain the queue, return it
        (NormalTaskSubmitter::RequestNewWorkerIfNeeded, :291).
        ``preacquired`` carries a multiplexed grant handed over by a
        sibling pipeline — the first iteration skips acquisition.

        Invariant: once a spec is popped from the queue it is ALWAYS resolved
        — completed, re-enqueued for retry, or failed — on every exit path,
        including cancellation and unexpected exceptions."""
        try:
            while True:
                if preacquired is not None:
                    leases, preacquired = [preacquired], None
                else:
                    with self._queue_lock:
                        if not self._task_queues.get(key):
                            return
                        probe_spec = self._task_queues[key][0]
                    leases, reason = await self._acquire_lease_shared(key, probe_spec)
                    if leases is None:
                        with self._queue_lock:
                            queue = self._task_queues.get(key) or []
                            specs, self._task_queues[key] = list(queue), []
                        reason = reason or "cluster infeasible or timeout"
                        for spec in specs:
                            self._fail_task(spec, RayTpuError(
                                f"Failed to lease a worker ({reason})"))
                        return
                # Extra multiplexed grants: hand each to a fresh pipeline
                # (bounded by the per-key cap); grants the cap or an
                # emptied queue leave unused go straight back.
                for lease in leases[1:]:
                    spawned = False
                    with self._queue_lock:
                        cap = get_config().max_pending_lease_requests_per_scheduling_category
                        if (self._task_queues.get(key)
                                and self._pipelines.get(key, 0) < cap):
                            self._pipelines[key] = self._pipelines.get(key, 0) + 1
                            spawned = True
                    if spawned:
                        self.io.run_coro(self._lease_pipeline(key, preacquired=lease))
                    else:
                        await self._return_lease(lease)
                worker_addr, worker_id, raylet_client, owns_client = leases[0]
                worker = RpcClient(worker_addr)
                # Spread tasks salt the key per task (key[-1] != 0): their
                # queue can never refill, so skip the grace.
                grace_s = 0.0 if key[-1] else get_config().lease_idle_grace_ms / 1000.0
                push_batch_cap = get_config().task_push_batch_size
                # ADAPTIVE batch size: batching amortizes per-RPC overhead
                # for cheap tasks but SERIALIZES execution within the batch
                # — two 1s tasks in one batch take 2s on one worker while
                # other leased workers idle. Start at 1 and ramp up only
                # while observed per-task time stays well under the RPC
                # overhead scale; any slow batch resets to 1
                # (_next_push_batch).
                cur_batch = 1

                pipeline_cap = get_config().max_pending_lease_requests_per_scheduling_category

                try:
                    while True:
                        with self._queue_lock:
                            queue = self._task_queues.get(key)
                            specs = _pop_push_batch(
                                queue, cur_batch, pipeline_cap) if queue else []
                        if not specs:
                            # Drained: hold the lease for a short grace so
                            # an immediate next submit reuses it (sync
                            # loops would otherwise pay a full lease
                            # acquire+return round trip per task).
                            if grace_s > 0:
                                deadline = time.monotonic() + grace_s
                                while not specs and time.monotonic() < deadline:
                                    await asyncio_sleep(0.002)
                                    with self._queue_lock:
                                        queue = self._task_queues.get(key)
                                        if queue:
                                            specs = _pop_push_batch(
                                                queue, cur_batch, pipeline_cap)
                            if not specs:
                                break
                        try:
                            push_t0 = time.monotonic()
                            worker_alive = await self._push_and_complete_batch(
                                specs, worker, worker_id)
                            per_task = (time.monotonic() - push_t0) / len(specs)
                            cur_batch = _next_push_batch(
                                cur_batch, per_task, push_batch_cap)
                        except BaseException as e:
                            # Never lose a popped spec: cancellation and
                            # unexpected errors fail them visibly.
                            for spec in specs:
                                self._fail_task(spec, RayTpuError(f"task submission aborted: {type(e).__name__}: {e}"))
                            raise
                        if not worker_alive:
                            # Worker died mid-push: drop this lease and loop
                            # back to _acquire_lease — retried specs must not
                            # be pushed to the same corpse.
                            break
                finally:
                    await worker.close()
                    try:
                        await raylet_client.call("ReturnWorker", {"worker_id": worker_id}, timeout=10.0)
                    except Exception:
                        pass
                    if owns_client:
                        await raylet_client.close()
        finally:
            with self._queue_lock:
                self._pipelines[key] = max(0, self._pipelines.get(key, 1) - 1)
                if self._task_queues.get(key):
                    self._pipelines[key] += 1
                    self.io.run_coro(self._lease_pipeline(key))
                elif self._pipelines.get(key, 0) == 0:
                    # Drop drained keys — spread tasks salt the key per
                    # task, so stale entries would accumulate forever.
                    self._pipelines.pop(key, None)
                    self._task_queues.pop(key, None)

    async def _acquire_lease(self, spec: TaskSpec, num_workers: int = 1):
        """Follow the lease/spillback protocol. A dead spillback target (its
        raylet unreachable) sends us back to the local raylet for a fresh
        placement — nodes can die between the spill decision and the hop —
        until an overall deadline expires.

        Returns ``(leases, reason)``: ``leases`` is a list of
        ``(worker_address, worker_id, raylet_client, owns_client)`` tuples
        — the first is the caller's; extras come from multiplexed grants
        (``num_workers`` > 1) and, when granted by a spillback raylet,
        each carry their own client — or ``None`` with the denial reason.
        The reason is RETURNED, never stashed on the instance: concurrent
        acquires for other scheduling keys must not see each other's
        denials (the old ``_last_lease_denial`` attribute raced exactly
        that way)."""
        import asyncio

        cfg = get_config()
        deadline = time.monotonic() + cfg.worker_register_timeout_s * 2
        # Lost-reply budget: a lease RPC that times out (dropped request
        # or reply — chaos or a real transient) is retried with a fresh
        # deadline window instead of failing every queued task; the
        # stranded grant, if any, is reclaimed raylet-side as an un-acked
        # orphan lease (ROADMAP 1c).
        timeout_retries = 3
        # Bounds waiting on a LOST reply; a slow-but-alive raylet keeps
        # streaming toward this cap legitimately (worker cold start).
        lease_rpc_timeout = (cfg.worker_register_timeout_s
                             + min(10.0, cfg.worker_register_timeout_s))
        raylet = self.raylet
        raylet_addr = self.raylet_address
        try:
            while time.monotonic() < deadline:
                for _hop in range(4):
                    payload = {"spec": spec.to_wire(), "spilled": _hop > 0}
                    # `spilled` marks follow-up hops so policies that
                    # redirect (spread) don't ping-pong the lease
                    if num_workers > 1:
                        payload["num_workers"] = num_workers
                    try:
                        reply = await raylet.call(
                            "RequestWorkerLease", payload,
                            timeout=lease_rpc_timeout,
                        )
                    except RpcError as e:
                        if raylet is self.raylet:
                            if "timed out" in str(e) and timeout_retries > 0:
                                timeout_retries -= 1
                                deadline = max(
                                    deadline,
                                    time.monotonic()
                                    + cfg.worker_register_timeout_s)
                                break
                            return None, "local raylet unreachable"
                        break  # spill target died: restart from local
                    if reply.get("granted"):
                        local = raylet is self.raylet
                        grants = [(reply["worker_address"], reply["worker_id"],
                                   raylet, not local)]
                        for g in reply.get("extra_grants") or ():
                            client = (self.raylet if local
                                      else RetryableRpcClient(raylet_addr))
                            grants.append((g["worker_address"], g["worker_id"],
                                           client, not local))
                        try:
                            # Confirm receipt of EVERY grant in one RPC:
                            # the raylet reclaims leases that are never
                            # acked (the reply may die on the wire —
                            # ROADMAP 1c).
                            await raylet.call(
                                "AckLease",
                                {"worker_id": reply["worker_id"],
                                 "worker_ids": [g[1] for g in grants[1:]]},
                                timeout=10.0)
                        except RpcError:
                            pass  # raylet reclaims; the lease still works
                        raylet = self.raylet  # returned clients kept by caller
                        return grants, ""
                    if reply.get("spillback"):
                        if raylet is not self.raylet:
                            await raylet.close()
                        raylet_addr = reply["node_address"]
                        raylet = RetryableRpcClient(raylet_addr)
                        continue
                    # definitive denial (infeasible / timeout / worker
                    # start failure): return the raylet's reason so the
                    # task error names the actual cause (e.g. a
                    # runtime_env plugin setup failure)
                    return None, reply.get("reason", "")
                if raylet is not self.raylet:
                    await raylet.close()
                    raylet = self.raylet
                    raylet_addr = self.raylet_address
                await asyncio.sleep(0.5)
            return None, ""
        finally:
            if raylet is not self.raylet:
                await raylet.close()

    async def _push_and_complete(self, spec: TaskSpec, worker: RpcClient, worker_id: str) -> bool:
        """Returns False when the worker died (the caller must drop the lease)."""
        # LEASED at dispatch: tasks pushed onto a reused lease never pass
        # through the raylet's grant path, so the owner stamps the lease
        # stage here (the GCS keeps the earliest LEASED ts per task).
        self.task_events.record(spec.task_id, spec.name, "LEASED",
                                kind=spec.kind, extra={"worker_id": worker_id})
        self._dispatched_to[spec.task_id] = worker.address
        if spec.task_id in self._cancelled_tasks:
            # Cancel raced the pop->dispatch window: the marker was set
            # after the queue scan missed this spec but before the
            # dispatch address was published — honoring it here (AFTER
            # publishing the address) closes the silent no-op window.
            self._dispatched_to.pop(spec.task_id, None)
            self._fail_task(spec, TaskCancelledError(spec.task_id.hex()[:12]))
            return True
        try:
            reply = await worker.call("PushTask", {"spec": spec.to_wire()}, timeout=None)
        except RpcError as e:
            # Worker died mid-task (PushNormalTask failure path →
            # FailOrRetryPendingTask, task_manager.h:491).
            self._dispatched_to.pop(spec.task_id, None)
            if spec.task_id in self._cancelled_tasks:
                # force-cancel kills the worker: that death IS the cancel
                self._fail_task(spec, TaskCancelledError(spec.task_id.hex()[:12]))
            elif self.task_manager.consume_retry(spec.task_id):
                logger.warning("Retrying task %s after worker failure: %s", spec.name, e)
                self._enqueue_task(spec)
            else:
                self._fail_task(spec, WorkerCrashedError(f"Worker died executing {spec.name}: {e}"))
            return False
        self._dispatched_to.pop(spec.task_id, None)
        if not await self._maybe_reexport(spec, reply):
            self._handle_task_reply(spec, reply)
        return True

    async def _push_and_complete_batch(self, specs: list, worker: RpcClient,
                                       worker_id: str) -> bool:
        """Push a batch of normal-task specs in ONE RPC (handle_PushTasks);
        single specs keep the one-task path. Returns False when the worker
        died — every spec of the batch is then retried or failed (the
        all-or-nothing RPC can't say which ran; same semantics as the
        single-task death path)."""
        if len(specs) == 1:
            return await self._push_and_complete(specs[0], worker, worker_id)
        for spec in specs:
            self.task_events.record(spec.task_id, spec.name, "LEASED",
                                    kind=spec.kind, extra={"worker_id": worker_id})
            self._dispatched_to[spec.task_id] = worker.address
        live = []
        for spec in specs:
            # Same cancel-raced-the-dispatch window as the single-task
            # path: honor markers set during the pop->dispatch gap.
            if spec.task_id in self._cancelled_tasks:
                self._dispatched_to.pop(spec.task_id, None)
                self._fail_task(spec, TaskCancelledError(spec.task_id.hex()[:12]))
            else:
                live.append(spec)
        specs = live
        if not specs:
            return True
        try:
            reply = await worker.call(
                "PushTasks", {"specs": [s.to_wire() for s in specs]}, timeout=None)
        except RpcError as e:
            for spec in specs:
                self._dispatched_to.pop(spec.task_id, None)
                if spec.task_id in self._cancelled_tasks:
                    self._fail_task(spec, TaskCancelledError(spec.task_id.hex()[:12]))
                elif self.task_manager.consume_retry(spec.task_id):
                    logger.warning("Retrying task %s after worker failure: %s", spec.name, e)
                    self._enqueue_task(spec)
                else:
                    self._fail_task(spec, WorkerCrashedError(
                        f"Worker died executing {spec.name}: {e}"))
            return False
        for spec, r in zip(specs, reply["replies"]):
            self._dispatched_to.pop(spec.task_id, None)
            if not await self._maybe_reexport(spec, r):
                self._handle_task_reply(spec, r)
        return True

    def _store_return_item(self, rid: ObjectID, ret: dict) -> None:
        """Store one executor-reported return (inline value or plasma
        marker) and register nested-ref containment/borrowing."""
        # The return value embeds nested refs: record containment (they
        # live while the return object lives here) and register as a
        # borrower with their owners (reference: nested-ref borrowing).
        contained = ret.get("contained") or []
        if contained:
            child_ids = []
            for c in contained:
                cid = ObjectID(c["id"])
                child_ids.append(cid)
                owner = c.get("owner", "")
                if owner and owner != self.address and self.refcounter.note_borrowed(cid, owner):
                    self.io.run_coro(self._send_borrow(owner, "AddBorrower", cid))
            self.refcounter.add_containment(rid, child_ids)
        if ret["t"] == "v":
            self.memory_store.put(rid, ret["meta"], ret["blob"])
            self.refcounter.set_size(rid, len(ret["blob"]))
        else:  # in plasma on executor's node
            node_id = ret["node_id"]
            self.refcounter.add_location(rid, node_id)
            self.memory_store.put_plasma_marker(rid, node_id.encode() if isinstance(node_id, str) else node_id)
            self.refcounter.set_size(rid, ret.get("size", 0))

    async def _maybe_reexport(self, spec: TaskSpec, reply: dict) -> bool:
        """Handle a worker's "function not in GCS" reply: the GCS lost the
        export (a crash inside the snapshot window). We still hold the
        function — re-export and resubmit (does NOT consume a user retry;
        nothing ran). Runs ON the io loop, so the KV write is awaited, not
        run_sync'd (that would deadlock the loop on itself)."""
        if not reply.get("function_missing"):
            return False
        fn = self.functions.cached(spec.function_id)
        if fn is None:
            self._fail_task(spec, RayTpuError(
                f"Function for task {spec.name} lost from the GCS and not "
                "cached by the owner"))
            return True
        logger.warning("Re-exporting function for task %s after GCS loss", spec.name)
        payload = cloudpickle.dumps(fn)
        await self.gcs.call(
            "KvPut",
            {"key": "fn:" + spec.function_id.hex(), "value": payload,
             "overwrite": True},
            timeout=30.0,
        )
        self._enqueue_task(spec)
        return True

    def _handle_task_reply(self, spec: TaskSpec, reply: dict) -> None:
        self._cancelled_tasks.discard(spec.task_id)
        self._record_task_span(spec, "ok")
        task_id = TaskID(spec.task_id)
        if spec.num_returns == -1:
            # Streaming task finished: items arrived via ReportGeneratorItem;
            # the reply only carries the final count (races with the last
            # report are fine — both paths are idempotent). The error fallback
            # covers a lost error report (owner briefly unreachable).
            stream = self._streams.get(spec.task_id)
            if stream is not None:
                err_wire = reply.get("stream_error")
                if err_wire:
                    err = serialization.deserialize(err_wire["meta"], err_wire["blob"])
                    if isinstance(err, RayTaskError):
                        err = err.as_instanceof_cause()
                    stream.fail(err)
                else:
                    stream.finish(reply.get("streamed", 0))
            self.task_manager.complete(spec.task_id)
            self._release_submitted_refs(spec)
            self._record_terminal(spec, reply)
            return
        for i, ret in enumerate(reply.get("returns", [])):
            rid = ObjectID.for_task_return(task_id, i + 1)
            self._store_return_item(rid, ret)
        self.task_manager.complete(spec.task_id)
        self._release_submitted_refs(spec)
        self._record_terminal(spec, reply)

    def _record_terminal(self, spec: TaskSpec, reply: dict) -> None:
        """Owner-side terminal status: the executor records FINISHED too,
        but its buffer dies unflushed when the worker is killed right
        after executing (chaos kill-on-lease, OOM kill) — the owner has
        the reply in hand, so the GCS must never show a settled task as
        non-terminal."""
        status = "FAILED" if reply.get("stream_error") else "FINISHED"
        self.task_events.record(spec.task_id, spec.name, status,
                                kind=spec.kind)

    def _fail_task(self, spec: TaskSpec, error: Exception) -> None:
        self._cancelled_tasks.discard(spec.task_id)
        self._record_task_span(spec, "error")
        self.task_events.record(spec.task_id, spec.name, "FAILED", kind=spec.kind,
                                extra={"error": str(error)[:200]})
        task_id = TaskID(spec.task_id)
        if spec.num_returns == -1:
            stream = self._streams.get(spec.task_id)
            if stream is not None:
                stream.fail(error)
            self.task_manager.fail(spec.task_id)
            self._release_submitted_refs(spec)
            return
        metadata, blob, _ = serialization.serialize_error(
            RayTaskError(spec.name, str(error), error)
        )
        for i in range(spec.num_returns):
            rid = ObjectID.for_task_return(task_id, i + 1)
            self.memory_store.put(rid, metadata, blob)
        self.task_manager.fail(spec.task_id)
        self._release_submitted_refs(spec)

    # ------------------------------------------------------------- actor API
    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str = "",
        num_cpus: float | None = None,
        resources: dict | None = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        concurrency_groups: dict | None = None,
        detached: bool = False,
        scheduling_strategy: dict | None = None,
        placement_group_id: bytes = b"",
        placement_group_bundle_index: int = -1,
        runtime_env: dict | None = None,
    ) -> bytes:
        with self._counter_lock:
            self._task_counter += 1
            counter = self._task_counter
        actor_id = ActorID.of(self.job_id, self.current_task_id, counter)
        fid = self.functions.export_cached(cls, "actor")
        task_id = TaskID.for_actor_creation_task(actor_id)
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = num_cpus
        res.setdefault("CPU", 1.0)
        spec = TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=f"{cls.__name__}.__init__",
            function_id=fid,
            kind=TASK_KIND_ACTOR_CREATION,
            args=self._serialize_args(args, kwargs),
            resources=res,
            owner_address=self.address,
            actor_id=actor_id.binary(),
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            concurrency_groups=dict(concurrency_groups or {}),
            scheduling_strategy=scheduling_strategy or {},
            placement_group_id=placement_group_id,
            placement_group_bundle_index=placement_group_bundle_index,
            runtime_env=self._accelerator_runtime_env(res, runtime_env),
        )
        self._attach_trace(spec)
        payload = {"spec": spec.to_wire(), "name": name, "detached": detached}
        state = _ActorState(actor_id.binary())
        state.serialized = (max_concurrency <= 1
                            and not spec.concurrency_groups)
        self._actors[actor_id.binary()] = state
        if name or detached:
            # Named/detached registration stays synchronous: the
            # name-taken error must surface from .remote() itself.
            reply = self._gcs_call("RegisterActor", payload)
            if reply.get("error"):
                self._actors.pop(actor_id.binary(), None)
                raise RayTpuError(reply["error"])
        else:
            # PIPELINED registration: unnamed actors cannot fail
            # RegisterActor (only name conflicts error), so a creation
            # storm fires the RPCs back-to-back instead of paying one
            # serial GCS round trip each — resolution and kill both wait
            # on register_future before trusting a GCS "not found".
            state.register_future = self.io.run_coro(
                self.gcs.call("RegisterActor", payload, 30.0))
        return actor_id.binary()

    def _actor_state(self, actor_id: bytes) -> _ActorState:
        state = self._actors.get(actor_id)
        if state is None:
            state = self._actors[actor_id] = _ActorState(actor_id)
        return state

    def submit_actor_task(
        self,
        actor_id: bytes,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int | str = 1,
        generator_backpressure: int = 0,
        concurrency_group: str = "",
    ) -> list[ObjectRef] | ObjectRefGenerator:
        state = self._actor_state(actor_id)
        streaming = num_returns == "streaming"
        with self._counter_lock:
            self._task_counter += 1
            counter = self._task_counter
        task_id = TaskID.for_actor_task(self.job_id, self.current_task_id, counter, ActorID(actor_id))
        with state.lock:
            seq_no = state.seq_no
            state.seq_no += 1
            incarnation = state.incarnation
        spec = TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=method_name,
            function_id=b"",
            kind=TASK_KIND_ACTOR_TASK,
            args=self._serialize_args(args, kwargs),
            num_returns=-1 if streaming else num_returns,
            generator_backpressure=generator_backpressure,
            owner_address=self.address,
            actor_id=actor_id,
            actor_method=method_name,
            seq_no=seq_no,
            concurrency_group=concurrency_group,
        )
        spec._incarnation = incarnation
        self._attach_trace(spec)
        if streaming:
            return self._submit_streaming(spec)
        return_ids = [ObjectID.for_task_return(task_id, i + 1) for i in range(num_returns)]
        for rid in return_ids:
            self.refcounter.add_owned_object(rid)
        self.task_manager.add_pending(spec, return_ids)
        self._record_submit(spec)
        wake = False
        with self._actor_submit_lock:
            self._actor_submit_q.append(spec)
            if not self._actor_submit_active:
                self._actor_submit_active = True
                wake = True
        if wake:
            self.io.run_coro(self._drain_actor_submits())
        return [ObjectRef(rid, self.address) for rid in return_ids]

    async def _drain_actor_submits(self) -> None:
        """Dispatch queued actor-task specs on the io loop, in submission
        order (seq numbers were assigned in ``submit_actor_task``, and the
        executor's per-caller buffer reorders stragglers anyway). Exits
        only after observing an empty queue under the lock, so a producer
        that appends after the last pop always sees ``active`` and wakes a
        new drainer.

        Specs addressed to the same SERIALIZED actor that are queued in
        the same sweep coalesce into one ``PushActorTasks`` RPC (executed
        strictly in seq order executor-side): a burst of K calls pays one
        wire round trip and one worker wakeup instead of K — the
        actor-call sibling of the normal-task push batch."""
        import asyncio

        batch_cap = get_config().task_push_batch_size
        while True:
            with self._actor_submit_lock:
                if not self._actor_submit_q:
                    self._actor_submit_active = False
                    return
                sweep = list(self._actor_submit_q)
                self._actor_submit_q.clear()
            groups: dict[bytes, list] = {}
            order: list[bytes] = []
            for spec in sweep:
                if spec.actor_id not in groups:
                    groups[spec.actor_id] = []
                    order.append(spec.actor_id)
                groups[spec.actor_id].append(spec)
            for aid in order:
                specs = groups[aid]
                batchable = (len(specs) > 1
                             and self._actors.get(aid) is not None
                             and self._actors[aid].serialized)
                if not batchable:
                    for spec in specs:
                        asyncio.ensure_future(self._submit_actor_task_async(spec))
                    continue
                for i in range(0, len(specs), batch_cap):
                    asyncio.ensure_future(
                        self._submit_actor_batch_async(specs[i:i + batch_cap]))
            # Let the dispatched sends make progress mid-burst.
            await asyncio.sleep(0)

    async def _submit_actor_batch_async(self, specs: list, attempts: int = 3) -> None:
        """Batched sibling of ``_submit_actor_task_async``: one
        PushActorTasks RPC for K in-seq-order calls to one serialized
        actor; per-spec replies settle exactly like the single path."""
        if len(specs) == 1:
            await self._submit_actor_task_async(specs[0])
            return
        state = self._actor_state(specs[0].actor_id)
        try:
            address = await self._resolve_actor(state)
        except ActorDiedError as e:
            for spec in specs:
                self._fail_task(spec, e)
            return
        with state.lock:
            for spec in specs:
                if getattr(spec, "_incarnation", state.incarnation) != state.incarnation:
                    spec.seq_no = state.seq_no
                    state.seq_no += 1
                    spec._incarnation = state.incarnation
        try:
            if state.client is None or state.client.address != address:
                state.client = RpcClient(address)
            reply = await state.client.call(
                "PushActorTasks", {"specs": [s.to_wire() for s in specs]},
                timeout=None)
            for spec, r in zip(specs, reply["replies"]):
                if r.get("error"):
                    self._fail_task(spec, RayTpuError(r["error"]))
                else:
                    self._handle_task_reply(spec, r)
        except RpcError as e:
            with state.lock:
                if state.address == address:  # first observer of this death
                    state.incarnation += 1
                    state.seq_no = 0
                    state.address = ""
                    state.client = None
            if getattr(e, "undelivered", False) and attempts > 0:
                await self._submit_actor_batch_async(specs, attempts - 1)
                return
            for spec in specs:
                self._fail_task(
                    spec, ActorDiedError(spec.actor_id.hex(),
                                         f"actor died while executing {spec.name}: {e}"))

    async def _submit_actor_task_async(self, spec: TaskSpec, attempts: int = 3) -> None:
        state = self._actor_state(spec.actor_id)
        try:
            address = await self._resolve_actor(state)
        except ActorDiedError as e:
            self._fail_task(spec, e)
            return
        # Sequence numbers are scoped to one actor incarnation: a spec
        # assigned before a restart gets a fresh seq for the new executor
        # (whose per-caller ordering buffer starts at 0 again).
        with state.lock:
            if getattr(spec, "_incarnation", state.incarnation) != state.incarnation:
                spec.seq_no = state.seq_no
                state.seq_no += 1
                spec._incarnation = state.incarnation
        try:
            if state.client is None or state.client.address != address:
                state.client = RpcClient(address)
            reply = await state.client.call("PushTask", {"spec": spec.to_wire()}, timeout=None)
            if reply.get("error"):
                self._fail_task(spec, RayTpuError(reply["error"]))
            else:
                self._handle_task_reply(spec, reply)
        except RpcError as e:
            with state.lock:
                if state.address == address:  # first observer of this death
                    state.incarnation += 1
                    state.seq_no = 0
                    state.address = ""
                    state.client = None
            # Never-delivered sends (connect failed — e.g. the cached
            # address points at a pre-restart incarnation) are side-effect
            # free: re-resolve and retry. Failures after delivery follow
            # reference semantics (actor_task_submitter.cc): the task FAILS
            # — the method may have executed and had side effects.
            if getattr(e, "undelivered", False) and attempts > 0:
                await self._submit_actor_task_async(spec, attempts - 1)
                return
            self._fail_task(
                spec, ActorDiedError(spec.actor_id.hex(), f"actor died while executing {spec.name}: {e}")
            )

    def _ensure_actor_watcher(self) -> None:
        """Start the actor-channel subscriber once (on first resolve):
        one long-poll on the GCS "actor" pub/sub channel replaces N
        pending actors x 10 GetActorInfo polls per second — during a
        creation storm the polling alone was a GCS-loop DoS, and the
        channel's batched fan-out delivers every transition in one wake."""
        if self._actor_watch_started:
            return
        self._actor_watch_started = True
        self.io.run_coro(self._actor_state_poller())

    async def _actor_state_poller(self) -> None:
        import asyncio

        cursor = 0  # replay is cheap (skips untracked actors) and has no
        # staleness hole for actors that settled before we subscribed
        while True:
            try:
                reply = await self.gcs.call(
                    "SubscribePoll",
                    {"cursors": {"actor": cursor}, "timeout": 30.0},
                    timeout=45.0)
            except Exception:
                await asyncio.sleep(1.0)
                continue
            msgs = (reply.get("messages") or {}).get("actor", [])
            for seq, msg in msgs:
                cursor = max(cursor, seq)
                try:
                    aid = bytes.fromhex(msg.get("actor_id", ""))
                except ValueError:
                    continue
                state = self._actors.get(aid)
                if state is None:
                    continue
                # Just signal: _resolve_actor re-reads authoritative
                # state via GetActorInfo, so every transition semantic
                # (ALIVE address, DEAD cause, RESTARTING) stays in one
                # place and a lost message only costs the safety re-poll.
                ev = state.changed
                if ev is not None:
                    ev.set()

    async def _resolve_actor(self, state: _ActorState) -> str:
        """Resolve the actor's current address: one authoritative
        GetActorInfo per state transition, parked on the actor-channel
        watcher between transitions (plus a 5s safety re-poll)."""
        import asyncio

        if state.address:
            return state.address
        self._ensure_actor_watcher()
        deadline = time.monotonic() + get_config().actor_resolve_timeout_s
        while time.monotonic() < deadline:
            if state.address:
                return state.address
            ev = state.changed
            if ev is None:
                ev = state.changed = asyncio.Event()
            ev.clear()
            reply = await self.gcs.call("GetActorInfo", {"actor_id": state.actor_id.hex()}, timeout=10.0)
            if not reply.get("found"):
                if state.register_future is not None \
                        and not state.register_future.done():
                    # Pipelined RegisterActor still in flight: "not
                    # found" just means our registration hasn't landed.
                    await asyncio_sleep(0.02)
                    continue
                raise ActorDiedError(state.actor_id.hex(), "actor not registered")
            if reply["state"] == "ALIVE" and reply["address"]:
                state.address = reply["address"]
                state.state = "ALIVE"
                return state.address
            if reply["state"] == "DEAD":
                state.state = "DEAD"
                raise ActorDiedError(state.actor_id.hex(), reply.get("death_cause", ""))
            remaining = deadline - time.monotonic()
            try:
                await asyncio.wait_for(ev.wait(), min(max(remaining, 0.01), 5.0))
            except asyncio.TimeoutError:
                pass
        raise ActorDiedError(state.actor_id.hex(), "timed out resolving actor address")

    def kill_actor(self, actor_id: bytes) -> None:
        self._await_registered(actor_id)
        self._gcs_call("KillActor", {"actor_id": actor_id.hex()})

    def _await_registered(self, actor_id: bytes, timeout: float = 30.0) -> None:
        """Ensure a pipelined RegisterActor has landed before a kill: a
        KillActor racing ahead of its registration would no-op and leak
        the actor once the register arrives."""
        state = self._actors.get(actor_id)
        fut = getattr(state, "register_future", None) if state else None
        if fut is not None:
            try:
                fut.result(timeout)
            except Exception:
                pass
            state.register_future = None

    def register_actor_handle(self, actor_id: bytes, owned: bool) -> None:
        with self._counter_lock:
            self._actor_handle_counts[actor_id] = self._actor_handle_counts.get(actor_id, 0) + 1
            if owned:
                self._owned_actors.add(actor_id)

    def deregister_actor_handle(self, actor_id: bytes) -> None:
        with self._counter_lock:
            count = self._actor_handle_counts.get(actor_id, 1) - 1
            self._actor_handle_counts[actor_id] = count
            should_kill = count <= 0 and actor_id in self._owned_actors
            if should_kill:
                self._owned_actors.discard(actor_id)
        if should_kill:
            try:
                state = self._actors.get(actor_id)
                reg = getattr(state, "register_future", None) if state else None

                async def _kill():
                    import asyncio

                    if reg is not None and not reg.done():
                        # A GC-kill racing ahead of the pipelined
                        # registration would no-op and leak the actor.
                        await asyncio.wrap_future(reg)
                    await self.gcs.call(
                        "KillActor", {"actor_id": actor_id.hex()}, 10.0)

                self.io.run_coro(_kill())
            except Exception:
                pass

    def get_actor_by_name(self, name: str) -> tuple[bytes, dict] | None:
        reply = self._gcs_call("GetActorByName", {"name": name})
        if not reply.get("found"):
            return None
        return bytes.fromhex(reply["actor_id"]), reply

    # --------------------------------------------------------- owner RPC svc
    async def handle_GetObjectStatus(self, p: dict) -> dict:
        oid = ObjectID(p["id"])
        wait = p.get("wait", False)
        timeout = p.get("timeout", 0.0)

        def _check() -> dict | None:
            entry = self.memory_store.get_if_exists(oid)
            if entry is None:
                return None
            if entry.in_plasma:
                return {"in_plasma": True, "locations": [l if isinstance(l, str) else l.hex() for l in self.refcounter.get_locations(oid)] or ([entry.node_id.decode()] if entry.node_id else [])}
            return {"inline": True, "metadata": entry.metadata, "blob": entry.blob}

        status = _check()
        if status is not None or not wait:
            return status or {"error": "unknown object"}
        # Event-driven long-poll: park an asyncio future on the store rather
        # than burning an executor thread per waiting borrower.
        import asyncio

        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def _on_ready(_oid):
            loop.call_soon_threadsafe(lambda: fut.done() or fut.set_result(True))

        if self.memory_store.add_callback(oid, _on_ready):
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                pass
            finally:
                self.memory_store.remove_callback(oid, _on_ready)
        return _check() or {"error": "timeout"}

    async def handle_AddObjectLocation(self, p: dict) -> dict:
        """A raylet that completed a transfer reports its new copy; later
        pullers then fan out across receivers instead of all draining the
        primary (the owner IS the object directory —
        ownership_based_object_directory.h)."""
        node_id = p["node_id"]
        self.refcounter.add_location(
            ObjectID(p["id"]),
            node_id if isinstance(node_id, str) else node_id.hex())
        return {}

    async def handle_RemoveObjectLocation(self, p: dict) -> dict:
        """A puller found a listed copy missing (evicted/dead holder):
        drop the stale directory entry (locations are added as hex
        strings by AddObjectLocation and as bytes by the return path —
        discard both forms)."""
        oid = ObjectID(p["id"])
        node_id = p["node_id"]
        hexed = node_id if isinstance(node_id, str) else node_id.hex()
        self.refcounter.remove_location(oid, hexed)
        self.refcounter.remove_location(oid, bytes.fromhex(hexed))
        return {}

    async def handle_GetObjectLocations(self, p: dict) -> dict:
        oid = ObjectID(p["id"])
        locations = [l if isinstance(l, str) else l.hex() for l in self.refcounter.get_locations(oid)]
        entry = self.memory_store.get_if_exists(oid)
        primary = ""
        if entry is not None and entry.in_plasma and entry.node_id:
            primary = entry.node_id.decode()
            if primary not in locations:
                locations.append(primary)  # the primary copy always counts
        return {"locations": locations, "primary": primary}

    async def handle_Ping(self, p: dict) -> dict:
        return {"worker_id": self.worker_id}

    # --------------------------------------------------- borrowing protocol
    async def handle_AddBorrower(self, p: dict) -> dict:
        oid = ObjectID(p["id"])
        self.refcounter.add_borrower(oid)
        # The borrower has registered: release one temporary return-hold.
        with self._borrow_holds_lock:
            holds = self._borrow_holds.get(oid.binary())
            had_hold = bool(holds)
            if holds:
                holds.pop()
                if not holds:
                    self._borrow_holds.pop(oid.binary(), None)
        if had_hold:
            self.refcounter.remove_borrower(oid)
        return {}

    async def handle_RemoveBorrower(self, p: dict) -> dict:
        self.refcounter.remove_borrower(ObjectID(p["id"]))
        return {}

    # ------------------------------------------------- streaming generators
    def release_stream(self, task_id: bytes) -> None:
        """Consumer is done with (or abandoned) a stream: drop the owner-side
        state and the stored-but-never-consumed items. The producer learns
        via its next report (``cancel``) and stops generating."""
        stream = self._streams.pop(task_id, None)
        if stream is None:
            return
        with stream.cond:
            consumed, num_items = stream.consumed, stream.num_items
        if not stream.finished:
            stream.fail(RayTpuError("streaming generator abandoned by consumer"))
        tid = TaskID(task_id)
        for i in range(consumed, num_items):
            # Unconsumed items never got a consumer-side ObjectRef, so the
            # refcounter will not free them — drop the store entries AND the
            # owned-object refcounter bookkeeping here (plasma copies fall
            # to LRU eviction).
            rid = ObjectID.for_task_return(tid, i + 1)
            self.memory_store.delete(rid)
            self.refcounter.drop(rid)

    async def handle_ReportGeneratorItem(self, p: dict) -> dict:
        """Executor reports one yielded item (or stream end/error) for a
        streaming task this worker owns (reference
        ``HandleReportGeneratorItemReturns``, task_manager.h:212)."""
        task_id = p["task_id"]
        stream = self._streams.get(task_id)
        if stream is None:
            # Unknown stream: the consumer abandoned it (or this owner
            # restarted) — tell the producer to stop generating.
            return {"consumed": p.get("index", 0) + 1, "cancel": True}
        if p.get("done"):
            if "error" in p:
                err = serialization.deserialize(p["error"]["meta"], p["error"]["blob"])
                if isinstance(err, RayTaskError):
                    err = err.as_instanceof_cause()
                stream.fail(err)
            else:
                stream.finish(p.get("total", 0))
            return {"consumed": stream.consumed}
        index = p["index"]
        rid = ObjectID.for_task_return(TaskID(task_id), index + 1)
        self.refcounter.add_owned_object(rid)
        self._store_return_item(rid, p["item"])
        stream.report_item(index)
        if self._streams.get(task_id) is not stream:
            # Raced with release_stream(): the consumer abandoned the stream
            # after we fetched it but before we stored this item, so the
            # release's drop loop (bounded by its num_items snapshot) missed
            # it. Clean up here — delete/drop are idempotent — and cancel.
            self.memory_store.delete(rid)
            self.refcounter.drop(rid)
            return {"consumed": index + 1, "cancel": True}
        return {"consumed": stream.consumed}

    async def handle_WaitGeneratorConsumed(self, p: dict) -> dict:
        """Executor-side backpressure long-poll: resolve once the consumer
        has taken ``until`` items, the stream ends, or a timeout passes.
        Parks an asyncio future on the stream — no thread per waiter."""
        import asyncio

        stream = self._streams.get(p["task_id"])
        if stream is None:
            return {"consumed": p.get("until", 0), "cancel": True}
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        if stream.add_async_waiter(p["until"], loop, fut):
            try:
                await asyncio.wait_for(fut, min(p.get("timeout", 10.0), 60.0))
            except asyncio.TimeoutError:
                pass
        with stream.cond:
            return {"consumed": stream.consumed, "cancel": stream.error is not None}

    # -------------------------------------------------- memory observability
    def memory_summary(self, limit: int | None = None) -> dict:
        """This process's reference table, `ray memory`-style: every live
        entry with size, classified ref type, creation callsite, and age,
        plus actor handles and local JAX HBM stats (observability/memory)."""
        from ..observability.memory import ACTOR_HANDLE, hbm_stats, process_rss_bytes

        cfg = get_config()
        entries, num_refs, total_bytes = self.refcounter.summary(
            limit if limit is not None else cfg.memory_summary_max_entries)
        with self._counter_lock:
            handles = {aid: n for aid, n in self._actor_handle_counts.items() if n > 0}
        for aid, count in handles.items():
            entries.append({
                "object_id": aid.hex(), "size": 0, "ref_type": ACTOR_HANDLE,
                "callsite": "", "age_s": 0.0, "local": count,
                "submitted": 0, "borrowers": 0, "contained_in": 0,
                "owned": aid in self._owned_actors,
            })
        return {
            "worker_id": self.worker_id,
            "node_id": self.node_id,
            "mode": self.mode,
            "pid": os.getpid(),
            "ts": time.time(),
            "num_refs": num_refs,
            "actor_handles": len(handles),
            "total_bytes": total_bytes,
            "rss_bytes": process_rss_bytes(),
            "hbm": hbm_stats(),
            "entries": entries,
        }

    async def handle_MemorySummary(self, p: dict) -> dict:
        """Live (un-buffered) summary for direct fan-out queries."""
        return {"summary": self.memory_summary(p.get("limit"))}

    async def handle_CaptureProfile(self, p: dict) -> dict:
        """On-demand ``jax.profiler`` trace capture (reference: `ray timeline`
        + the dashboard profiler button): runs start_trace/stop_trace around
        a sleep in an executor thread and returns the artifact directory
        (xplane.pb + trace.json.gz, loadable in XProf/Perfetto)."""
        import asyncio
        import tempfile

        cfg = get_config()
        duration = min(float(p.get("duration", 2.0)), cfg.profile_max_duration_s)
        outdir = p.get("output_dir") or tempfile.gettempdir()
        path = os.path.join(
            outdir, f"raytpu_profile_{self.worker_id[:8]}_{int(time.time())}")
        with self._exec_lock:
            if getattr(self, "_profiling", False):
                return {"error": "a profile capture is already in progress"}
            self._profiling = True

        def _capture() -> None:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            try:
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()

        try:
            await asyncio.get_running_loop().run_in_executor(None, _capture)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        finally:
            with self._exec_lock:
                self._profiling = False
        return {"path": path, "worker_id": self.worker_id,
                "node_id": self.node_id, "duration": duration}

    async def _task_event_flusher(self) -> None:
        import asyncio

        interval = get_config().task_events_flush_interval_ms / 1000.0
        last_memory_report = 0.0
        while True:
            await asyncio.sleep(interval)
            # Piggyback the periodic memory summary on the flush cadence
            # (re-reads the config so tests can retune it live).
            mem_interval = get_config().memory_report_interval_ms / 1000.0
            now = time.monotonic()
            if mem_interval > 0 and now - last_memory_report >= mem_interval:
                last_memory_report = now
                try:
                    self.task_events.record_memory(self.memory_summary())
                except Exception:
                    pass
            events, dropped = self.task_events.drain()
            if not events and not dropped:
                continue
            try:
                await self.gcs.call(
                    "AddTaskEvents", {"events": events, "dropped": dropped}, timeout=10.0
                )
            except Exception:
                pass

    async def _global_gc_poller(self) -> None:
        """Run ``gc.collect()`` when the GCS broadcasts a global GC —
        scheduling is starved by resources that garbage may be pinning
        (reference ``ray._private.internal_api.global_gc`` / core_worker
        TriggerGlobalGC). Typical culprit: actor handles captured in
        exception→traceback→frame reference cycles."""
        import asyncio
        import gc

        cursor = None
        while True:
            try:
                reply = await self.gcs.call(
                    "PollGlobalGc", {"cursor": cursor, "timeout": 30.0}, timeout=40.0
                )
            except Exception:
                await asyncio.sleep(1.0)
                continue
            cursor = reply.get("cursor", cursor)
            if reply.get("triggered"):
                # NEVER collect on the io loop thread: finalizers (e.g.
                # CompiledDAG.__del__ → teardown) may run_sync back onto
                # this very loop — a guaranteed self-deadlock.
                await asyncio.get_running_loop().run_in_executor(None, gc.collect)

    async def _borrow_hold_sweeper(self) -> None:
        """Failsafe: drop return-holds whose caller never registered (it
        died before processing the reply)."""
        import asyncio

        while True:
            await asyncio.sleep(get_config().borrow_sweep_interval_s)
            now = time.monotonic()
            expired: list[bytes] = []
            with self._borrow_holds_lock:
                for key, holds in list(self._borrow_holds.items()):
                    while holds and holds[0] <= now:
                        holds.pop(0)
                        expired.append(key)
                    if not holds:
                        self._borrow_holds.pop(key, None)
            for key in expired:
                self.refcounter.remove_borrower(ObjectID(key))

    # ------------------------------------------------------------ executor
    async def handle_CancelTask(self, p: dict) -> dict:
        """Owner asks this EXECUTOR to cancel a running task. Non-force:
        raise TaskCancelledError asynchronously in the executing thread
        (CPython PyThreadState_SetAsyncExc — lands at the next bytecode).
        Force: the whole worker process exits; the owner's push RPC fails,
        and the cancelled marker turns that death into TaskCancelledError
        instead of a retry."""
        import ctypes

        task_id = p["task_id"]
        if p.get("force"):
            import asyncio

            import os as _os
            import signal as _signal

            # give the reply a moment to flush, then die hard
            asyncio.get_running_loop().call_later(
                0.05, lambda: _os.kill(_os.getpid(), _signal.SIGKILL))
            return {"found": True, "killing": True}
        with self._exec_lock:
            ident = self._exec_threads.get(task_id)
            if ident is None:
                # dispatched but not yet executing: mark so _execute_task
                # refuses to run the body when it gets the thread. Bound
                # the set: markers for tasks that never execute here
                # (e.g. re-routed after a lease change) are evicted
                # oldest-first past the cap instead of leaking.
                self._cancelled_inbound[task_id] = None
                while len(self._cancelled_inbound) > 4096:
                    self._cancelled_inbound.pop(
                        next(iter(self._cancelled_inbound)))
                return {"found": False, "pending": True}
            # under the lock the thread cannot pop its entry, so the
            # async exception targets the right task
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError))
        return {"found": True}

    async def handle_LeaseProbe(self, p: dict) -> dict:
        """Raylet probe before an orphan-lease reclaim: is this worker
        actually serving its lease (executing, hosting an actor, or still
        receiving pushes)?"""
        with self._exec_lock:
            executing = bool(self._exec_threads)
        return {
            "executing": executing or self.actor_instance is not None,
            "pushes_total": self._pushes_total,
        }

    async def handle_PushTask(self, p: dict) -> dict:
        import asyncio

        self._pushes_total += 1
        spec = TaskSpec.from_wire(p["spec"])
        logger.debug("PushTask recv: %s kind=%s seq=%s", spec.name, spec.kind, spec.seq_no)
        loop = asyncio.get_running_loop()
        if spec.kind == TASK_KIND_ACTOR_TASK:
            return await self._execute_actor_task(spec, loop)
        return await loop.run_in_executor(None, self._execute_task, spec)

    async def handle_PushActorTasks(self, p: dict) -> dict:
        """Batched PushTask for ACTOR tasks: K in-order calls from one
        caller to this (serialized) actor in one RPC. Each spec still
        passes through the per-caller sequencing buffer and the actor
        semaphore — execution semantics are identical to K single pushes
        on the same ordered connection; only the wire round trips and
        process wakeups collapse."""
        import asyncio

        self._pushes_total += 1
        specs = [TaskSpec.from_wire(w) for w in p["specs"]]
        loop = asyncio.get_running_loop()
        return {"replies": [await self._execute_actor_task(spec, loop)
                            for spec in specs]}

    async def handle_PushTasks(self, p: dict) -> dict:
        """Batched PushTask for normal tasks: K specs in one RPC, executed
        sequentially in ONE executor-thread hop, K replies in one response.
        The per-task cost of the batch-submit path is otherwise dominated
        by per-hop RPC + thread-handoff overhead, not execution."""
        import asyncio

        self._pushes_total += 1
        specs = [TaskSpec.from_wire(w) for w in p["specs"]]
        loop = asyncio.get_running_loop()

        def run_all():
            return [self._execute_task(s) for s in specs]

        return {"replies": await loop.run_in_executor(None, run_all)}

    async def _execute_actor_task(self, spec: TaskSpec, loop) -> dict:
        # Per-caller submission-order delivery with an out-of-order arrival
        # buffer (transport/actor_scheduling_queue.cc). Tasks are RELEASED
        # to the executor in sequence order, but the next seq is unblocked
        # as soon as this one starts — the actor's max_concurrency
        # semaphore (not the ordering buffer) bounds concurrent execution,
        # so max_concurrency=1 still serializes while concurrent actors
        # overlap (reference: threaded/async scheduling queues).
        caller = spec.owner_address
        while spec.seq_no > self._actor_next_seq.get(caller, 0):
            fut = loop.create_future()
            self._actor_ooo_buffer[(caller, spec.seq_no)] = fut
            await fut
        if self._actor_max_concurrency <= 1 and not self._actor_group_sems:
            # Serialized actor: strict execution order — complete before
            # releasing the next sequence number. (An actor WITH
            # concurrency groups is inherently concurrent: grouped calls
            # must not serialize behind the default pool.)
            result = await loop.run_in_executor(None, self._execute_task, spec)
            self._release_next_actor_seq(caller, spec.seq_no)
            return result
        # Concurrent actor: release the next seq as soon as this task is
        # handed to the executor; the max_concurrency semaphore bounds
        # parallelism.
        exec_fut = loop.run_in_executor(None, self._execute_task, spec)
        self._release_next_actor_seq(caller, spec.seq_no)
        return await exec_fut

    def _release_next_actor_seq(self, caller: str, seq_no: int) -> None:
        self._actor_next_seq[caller] = max(self._actor_next_seq.get(caller, 0), seq_no + 1)
        nxt = self._actor_ooo_buffer.pop((caller, self._actor_next_seq[caller]), None)
        if nxt is not None and not nxt.done():
            nxt.set_result(True)

    def _execute_task(self, spec: TaskSpec) -> dict:
        """ExecuteTask (core_worker.cc:3229) + Cython execute_task
        (_raylet.pyx:1726) equivalent."""
        prev_task_id = self.current_task_id
        self.current_task_id = TaskID(spec.task_id)
        self.task_events.record(spec.task_id, spec.name, "RUNNING", kind=spec.kind)
        with self._exec_lock:
            if spec.task_id in self._cancelled_inbound:
                # cancel arrived before execution (batched push / pool
                # backlog): never run the body
                self._cancelled_inbound.pop(spec.task_id, None)
                self.current_task_id = prev_task_id
                metadata, blob, _ = serialization.serialize_error(
                    RayTaskError(spec.name, "task cancelled",
                                 TaskCancelledError(spec.task_id.hex()[:12])))
                if spec.num_returns == -1:
                    # Streaming task: reply in stream form so the owner
                    # raises TaskCancelledError at the consumer instead
                    # of finishing a clean empty stream.
                    return {"returns": [], "streamed": 0,
                            "stream_error": {"meta": metadata, "blob": blob}}
                return {"returns": [
                    {"t": "v", "meta": metadata, "blob": blob, "contained": []}
                    for _ in range(max(spec.num_returns, 1))]}
            self._exec_threads[spec.task_id] = threading.get_ident()
        # Install the spec's trace context for the duration of execution:
        # spans recorded by user code (and nested submits, engine requests,
        # serve batches) chain under this task's execute span.
        from ..observability import tracing

        _exec_ctx = _trace_prev = None
        _exec_start = time.time()
        if spec.trace_id:
            _exec_ctx = tracing.TraceContext(
                spec.trace_id, tracing.new_span_id(), spec.span_id)
            _trace_prev = tracing.set_current(_exec_ctx)
        try:
            args, kwargs = self._deserialize_args(spec)
            if spec.kind == TASK_KIND_ACTOR_CREATION:
                cls, _tag = self.functions.get(spec.function_id)
                self.actor_instance = cls(*args, **kwargs)
                self.actor_id = spec.actor_id
                self._actor_next_seq = {}
                # Actor-wide concurrency limit: sequencing is per-caller, but
                # calls from DIFFERENT callers must still respect
                # max_concurrency (default 1 = serialized actor).
                self._actor_max_concurrency = max(1, spec.max_concurrency)
                self._actor_sem = threading.Semaphore(self._actor_max_concurrency)
                # Named per-method pools (reference
                # concurrency_group_manager.cc): each group gets its own
                # semaphore; grouped calls never contend with the default
                # pool or with other groups.
                self._actor_group_sems = {
                    g: threading.Semaphore(max(1, int(n)))
                    for g, n in (spec.concurrency_groups or {}).items()}
                # Terminal status for the creation task: without this every
                # successful actor creation stays RUNNING in list_tasks()
                # forever (and trips any "all tasks settled" invariant).
                self.task_events.record(spec.task_id, spec.name, "FINISHED",
                                        kind=spec.kind)
                return {"returns": []}
            if spec.kind == TASK_KIND_ACTOR_TASK:
                if self.actor_instance is None:
                    return {"error": "actor instance not initialized"}
                if spec.actor_method == "__ray_call__":
                    # Internal escape hatch (reference: actor __ray_call__):
                    # run a shipped function with the instance as first arg.
                    # Compiled DAGs use it to install their executor loop.
                    fn, args = args[0], args[1:]
                    method = functools.partial(fn, self.actor_instance)
                else:
                    method = getattr(self.actor_instance, spec.actor_method)
                group = spec.concurrency_group
                if not group:
                    # per-method default declared with @method(
                    # concurrency_group=...) — resolved here, executor
                    # side, where the class definition lives
                    fn = getattr(method, "__func__", method)
                    group = getattr(fn, "__ray_concurrency_group__", "")
                sem = self._actor_group_sems.get(group) or self._actor_sem
                if sem is not None:
                    with sem:
                        # run-to-completion INSIDE the semaphore: an async
                        # method returns its coroutine instantly, so the
                        # asyncio.run must also be covered or
                        # max_concurrency=1 would not serialize async actors
                        result = _run_to_completion(method(*args, **kwargs))
                else:
                    result = _run_to_completion(method(*args, **kwargs))
            else:
                try:
                    fn, _tag = self.functions.get(spec.function_id)
                except FunctionMissingError:
                    # GCS lost the export (crash inside the snapshot
                    # window): ask the owner to re-export + resubmit.
                    return {"function_missing": True}
                result = _run_to_completion(fn(*args, **kwargs))
            if spec.num_returns == -1:
                # Streaming generator: iterate + report items; the reply
                # carries only the final count (events recorded inside).
                return self._stream_generator_results(spec, result)
            reply = {"returns": self._serialize_returns(spec, result)}
            self.task_events.record(spec.task_id, spec.name, "FINISHED", kind=spec.kind)
            return reply
        except Exception as e:
            tb = traceback.format_exc()
            self.task_events.record(spec.task_id, spec.name, "FAILED", kind=spec.kind,
                                    extra={"error": f"{type(e).__name__}: {e}"})
            self._publish_task_error(spec, e, tb)
            if spec.kind == TASK_KIND_ACTOR_CREATION:
                return {"error": f"{type(e).__name__}: {e}\n{tb}"}
            metadata, blob, _ = serialization.serialize_error(RayTaskError(spec.name, tb, e))
            if spec.num_returns == -1:
                # Failure before the generator started (bad args, arity,
                # missing function): surface it on the stream — the normal
                # per-index error path never ran.
                try:
                    self.io.run_sync(self._owner_client(spec.owner_address).call(
                        "ReportGeneratorItem",
                        {"task_id": spec.task_id, "done": True, "total": 0,
                         "error": {"meta": metadata, "blob": blob}},
                        timeout=30.0,
                    ))
                except Exception:
                    pass
                return {"returns": [], "streamed": 0,
                        "stream_error": {"meta": metadata, "blob": blob}}
            return {"returns": [{"t": "v", "meta": metadata, "blob": blob} for _ in range(spec.num_returns)]}
        finally:
            if _exec_ctx is not None:
                tracing.record_span(tracing.make_span(
                    f"execute {spec.name}", "task", _exec_start, time.time(),
                    spec.trace_id, spec.span_id, _exec_ctx.span_id,
                    attrs={"task_id": spec.task_id.hex(),
                           "worker_id": self.worker_id}))
                tracing.set_current(_trace_prev)
            with self._exec_lock:
                self._exec_threads.pop(spec.task_id, None)
            self.current_task_id = prev_task_id

    def _publish_task_error(self, spec: TaskSpec, error: Exception, tb: str) -> None:
        """Executor-side publish_error_to_driver: a raising task's full
        traceback reaches the GCS error-info channel (→ the driver's log
        and ``state.list_errors()``), not just the serialized return value.
        Fire-and-forget — diagnostics never blocks or fails execution."""
        if isinstance(error, TaskCancelledError):
            return  # a requested cancel is not an error condition
        try:
            from ..diagnostics.errors import make_event

            etype = ("actor_creation_failure"
                     if spec.kind == TASK_KIND_ACTOR_CREATION else "task_failure")
            actor_id = spec.actor_id or b""
            event = make_event(
                etype,
                f"{spec.name}: {type(error).__name__}: {error}",
                source="worker",
                traceback=tb,
                node_id=self.node_id,
                worker_id=self.worker_id,
                actor_id=actor_id.hex() if isinstance(actor_id, bytes) else actor_id,
                job_id=str(self.job_id.int_value()),
            )
            self.io.run_coro(self.gcs.call("PublishError", {"event": event}, 10.0))
        except Exception:
            pass

    def _deserialize_args(self, spec: TaskSpec) -> tuple[tuple, dict]:
        args: list = []
        kwargs: dict = {}
        ref_args: list[tuple[int | str, ObjectRef]] = []
        for entry in spec.args:
            if entry["t"] == "v":
                value = serialization.deserialize(entry["meta"], entry["blob"])
            else:
                ref = ObjectRef(ObjectID(entry["id"]), entry["owner"], _add_local_ref=False)
                value = self._get_one(ref, deadline=None, pull_class="task_arg")
            if "key" in entry:
                kwargs[entry["key"]] = value
            else:
                args.append(value)
        return tuple(args), kwargs

    def _serialize_returns(self, spec: TaskSpec, result: Any) -> list:
        cfg = get_config()
        if spec.num_returns == 1:
            results = [result]
        else:
            results = list(result)
            if len(results) != spec.num_returns:
                raise ValueError(f"Task {spec.name} returned {len(results)} values, expected {spec.num_returns}")
        task_id = TaskID(spec.task_id)
        return [self._serialize_return_value(task_id, i, v) for i, v in enumerate(results)]

    def _serialize_return_value(self, task_id: TaskID, index: int, value: Any) -> dict:
        """Serialize one task return: inline entry for small values, shm
        store + plasma marker for large ones."""
        cfg = get_config()
        s = serialization.serialize_value(value)
        metadata = s.metadata
        wire_contained = self._hold_returned_refs(s.contained)
        if s.nbytes <= cfg.max_inline_object_size:
            entry = {"t": "v", "meta": metadata, "blob": s.to_blob()}
        else:
            rid = ObjectID.for_task_return(task_id, index + 1)
            self._plasma_put(rid, metadata, s)
            entry = {"t": "p", "node_id": self.node_id, "size": s.nbytes}
        if wire_contained:
            entry["contained"] = wire_contained
        return entry

    def _stream_generator_results(self, spec: TaskSpec, gen: Any) -> dict:
        """Execute a streaming task's generator, reporting every yielded
        item to the owner as it is produced (reference: streaming-generator
        executor protocol, _raylet.pyx execute_streaming_generator).

        Runs in the executor thread AFTER the task function returned its
        generator. Item object IDs are deterministic task-return IDs, so a
        retried execution re-reports idempotently."""
        task_id = TaskID(spec.task_id)
        client = self._owner_client(spec.owner_address)
        count = 0
        consumed = 0
        bp = spec.generator_backpressure
        cancelled = False
        try:
            it = _iter_generator(gen)
            for value in it:
                entry = self._serialize_return_value(task_id, count, value)
                reply = self.io.run_sync(client.call(
                    "ReportGeneratorItem",
                    {"task_id": spec.task_id, "index": count, "item": entry},
                    timeout=get_config().generator_report_timeout_s,
                ))
                consumed = reply.get("consumed", consumed)
                count += 1
                if reply.get("cancel"):
                    # Consumer abandoned the stream: stop producing.
                    cancelled = True
                    it.close()
                    break
                # Backpressure: pause once `bp` reported items sit unconsumed
                # (reference _generator_backpressure_num_objects).
                while bp > 0 and count - consumed >= bp:
                    r2 = self.io.run_sync(client.call(
                        "WaitGeneratorConsumed",
                        {"task_id": spec.task_id, "until": count - bp + 1,
                         "timeout": get_config().generator_wait_consumed_poll_s},
                        timeout=get_config().generator_wait_consumed_poll_s + 30.0,
                    ))
                    consumed = r2.get("consumed", consumed)
                    if r2.get("cancel"):
                        cancelled = True
                        it.close()
                        break
                if cancelled:
                    break
        except Exception as e:
            tb = traceback.format_exc()
            self.task_events.record(spec.task_id, spec.name, "FAILED", kind=spec.kind,
                                    extra={"error": f"{type(e).__name__}: {e}"})
            metadata, blob, _ = serialization.serialize_error(RayTaskError(spec.name, tb, e))
            try:
                self.io.run_sync(client.call(
                    "ReportGeneratorItem",
                    {"task_id": spec.task_id, "done": True, "total": count,
                     "error": {"meta": metadata, "blob": blob}},
                    timeout=30.0,
                ))
            except Exception:
                pass  # owner gone: nothing to report to
            return {"returns": [], "streamed": count,
                    "stream_error": {"meta": metadata, "blob": blob}}
        if not cancelled:
            try:
                self.io.run_sync(client.call(
                    "ReportGeneratorItem",
                    {"task_id": spec.task_id, "done": True, "total": count},
                    timeout=30.0,
                ))
            except Exception:
                pass
        self.task_events.record(spec.task_id, spec.name, "FINISHED", kind=spec.kind)
        return {"returns": [], "streamed": count}

    def _hold_returned_refs(self, contained: list) -> list[dict]:
        """A return value embeds ObjectRefs: take a temporary borrower hold
        on each ref we own so it survives until the caller registers as a
        borrower (released in handle_AddBorrower, or by the expiry sweep if
        the caller died). Returns the wire descriptors."""
        wire = []
        now = time.monotonic()
        for r in contained:
            oid = r.id()
            owner = r.owner_address or self.address
            if self.refcounter.is_owned(oid):
                owner = self.address
                self.refcounter.add_borrower(oid)
                with self._borrow_holds_lock:
                    self._borrow_holds.setdefault(oid.binary(), []).append(
                        now + get_config().borrow_hold_ttl_s)
            wire.append({"id": oid.binary(), "owner": owner})
        return wire

    async def handle_Exit(self, p: dict) -> dict:
        import asyncio

        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {}


def asyncio_sleep(t: float):
    import asyncio

    return asyncio.sleep(t)


def _pop_push_batch(queue: list, cur_batch: int, pipeline_cap: int) -> list:
    """Pop the next push batch off a lease pipeline's queue. Load-bearing
    invariants (unit-tested in test_core_throughput.py):

    * Batched pushes defer every reply to the end of the batch, so a spec
      with an ObjectRef arg must ship ALONE: its dependency may be an
      earlier task of the same batch, whose result only reaches the owner
      with the reply — batching them would deadlock the chain.
    * A SHORT queue (no more specs than pipelines allowed) is parallel
      opportunity, not batching material: other lease pipelines can run
      those specs on other workers concurrently — only batch genuine
      backlog.
    """
    limit = cur_batch if len(queue) > pipeline_cap else 1
    specs: list = []
    while queue and len(specs) < limit:
        has_ref = any(e.get("t") == "r" for e in queue[0].args)
        if has_ref and specs:
            break
        specs.append(queue.pop(0))
        if has_ref:
            break
    return specs


def _next_push_batch(cur_batch: int, per_task_s: float, cap: int) -> int:
    """Adaptive push-batch ramp: grow (×4 up to ``cap``) only while the
    observed per-task time stays well under the RPC-overhead scale; ANY
    slow batch resets to 1 — a batch serializes execution on one worker,
    so batching slow tasks wastes every other leased worker."""
    if per_task_s < 0.005:
        return min(cap, cur_batch * 4)
    return 1


def _iter_generator(gen):
    """Drive a sync or async generator from the executor thread, yielding
    items synchronously (async generators get a private event loop)."""
    if hasattr(gen, "__anext__"):
        import asyncio

        loop = asyncio.new_event_loop()
        try:
            while True:
                try:
                    yield loop.run_until_complete(gen.__anext__())
                except StopAsyncIteration:
                    break
        finally:
            loop.close()
    elif hasattr(gen, "__next__") or hasattr(gen, "__iter__"):
        yield from gen
    else:
        raise TypeError(
            f"Task declared num_returns='streaming' must return a generator, got {type(gen).__name__}"
        )


def _run_to_completion(result):
    """async actor/task functions run on their own loop in this executor
    thread (reference: fiber scheduling queues, transport/fiber.h)."""
    if inspect.iscoroutine(result):
        import asyncio

        return asyncio.run(result)
    return result


# ---------------------------------------------------------------- global API
_global_worker: CoreWorker | None = None
_global_lock = threading.Lock()


def global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RayTpuError("ray_tpu.init() has not been called")
    return _global_worker


def set_global_worker(worker: CoreWorker | None) -> None:
    global _global_worker
    _global_worker = worker
