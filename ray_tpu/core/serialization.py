"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Equivalent of the reference's ``python/ray/_private/serialization.py:122``
(``SerializationContext``): values are cloudpickled with protocol 5 so large
contiguous buffers (numpy / jax host arrays / arrow) are extracted
out-of-band and written verbatim, enabling zero-copy reads from the
shared-memory store. The on-wire layout is one contiguous blob:

    [u32 magic][u32 n_buffers][n_buffers x (u64 offset, u64 size)]
    [padding to 64B][buffer 0 = pickle stream][buffer 1..][...]

Buffers are 64-byte aligned so vectorized consumers can use them in place.
Nested ``ObjectRef`` capture is supported via a thread-local context the
owner installs around serialize/deserialize (the reference does this for
borrowed-ref bookkeeping, ``reference_count.h:66``).
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
from typing import Any, Callable

import cloudpickle

_MAGIC = 0x52545055  # "RTPU"
_ALIGN = 64
_HEADER = struct.Struct("<II")
_ENTRY = struct.Struct("<QQ")


class PlasmaBuffer:
    """Buffer-protocol wrapper tying a shm read to the deserialized value's
    lifetime.

    Values deserialized from plasma alias arena memory (zero-copy numpy);
    the store must not spill or evict the object while any view is alive.
    The reference solves this with plasma ``Buffer`` objects that hold a
    client ref until GC'd (``python/ray/_private/serialization.py:122`` via
    ``plasma::Buffer``); here the PEP-688 buffer protocol counts live
    exports and fires ``on_release`` when the last derived view (including
    pickle5-reconstructed arrays) is released.
    """

    __slots__ = ("_mv", "_on_release", "_exports")

    def __init__(self, mv: memoryview, on_release: Callable[[], None] | None = None):
        self._mv = mv
        self._on_release = on_release
        self._exports = 0

    def __buffer__(self, flags: int) -> memoryview:
        self._exports += 1
        return memoryview(self._mv)

    def __release_buffer__(self, view: memoryview) -> None:
        self._exports -= 1
        if self._exports == 0 and self._on_release is not None:
            cb, self._on_release = self._on_release, None
            try:
                cb()
            except Exception:
                pass

    def __del__(self):
        # Never exported (e.g. deserialize raised before unframing).
        if self._on_release is not None:
            cb, self._on_release = self._on_release, None
            try:
                cb()
            except Exception:
                pass

    def copy_and_release(self) -> bytes:
        """Pre-3.12 fallback (no PEP-688 ``__buffer__``): copy out of the
        arena and release the read pin eagerly — loses zero-copy, keeps
        correctness."""
        data = bytes(self._mv)
        if self._on_release is not None:
            cb, self._on_release = self._on_release, None
            try:
                cb()
            except Exception:
                pass
        return data


_HAS_PEP688 = sys.version_info >= (3, 12)

# Metadata tags (reference: ray_constants OBJECT_METADATA_TYPE_*).
META_PICKLE5 = b"PICKLE5"
META_ERROR = b"ERROR"
META_ACTOR_HANDLE = b"ACTOR_HANDLE"
META_RAW = b"RAW"


class _SerializationThreadContext(threading.local):
    def __init__(self):
        self.contained_refs: list | None = None
        self.outer_object_id = None


_ctx = _SerializationThreadContext()

# Registered by object_ref.py to avoid a circular import: maps ObjectRef
# instances through pickling while recording containment.
_object_ref_reducer: Callable | None = None
_object_ref_class: type | None = None


def register_object_ref_serializer(ref_class: type, reducer: Callable) -> None:
    global _object_ref_class, _object_ref_reducer
    _object_ref_class = ref_class
    _object_ref_reducer = reducer


def record_contained_ref(ref) -> None:
    if _ctx.contained_refs is not None:
        _ctx.contained_refs.append(ref)


class _Pickler(cloudpickle.Pickler):
    def reducer_override(self, obj):
        if _object_ref_class is not None and type(obj) is _object_ref_class:
            record_contained_ref(obj)
            return _object_ref_reducer(obj)
        # Delegate to cloudpickle's own reducer_override — it is how
        # closures/lambdas/local classes get pickled by value; returning
        # NotImplemented here would silently fall back to stock pickle's
        # by-reference handling, which breaks on any <locals> object.
        return super().reducer_override(obj)


class Serialized:
    """A serialized value as its raw buffer list — framing deferred.

    The frame (header + aligned buffers) can be written DIRECTLY into a
    destination (``write_into`` — e.g. the shm arena via mmap) without
    ever materializing the concatenated blob: for a 10 MB put that is
    the difference between one copy and three (BytesIO concat, bytearray
    frame, bytes() of it, mmap write)."""

    __slots__ = ("metadata", "buffers", "contained")

    def __init__(self, metadata: bytes, buffers: list, contained: list):
        self.metadata = metadata
        self.buffers = buffers
        self.contained = contained

    @property
    def nbytes(self) -> int:
        if self.metadata == META_RAW:
            return memoryview(self.buffers[0]).nbytes
        return framed_size(self.buffers)

    def to_blob(self) -> bytes:
        if self.metadata == META_RAW:
            return bytes(self.buffers[0])
        return _frame(self.buffers)

    def write_into(self, view: memoryview) -> int:
        if self.metadata == META_RAW:
            mv = memoryview(self.buffers[0]).cast("B")
            view[: mv.nbytes] = mv
            return mv.nbytes
        return frame_into(view, self.buffers)


def serialize_value(value: Any) -> Serialized:
    """Serialize ``value`` keeping its raw buffers separate (pickle5
    out-of-band). Top-level ``bytes`` take the RAW path — no pickle at
    all (the C pickler never consults ``reducer_override`` for bytes, so
    they'd otherwise be copied through the pickle stream)."""
    if type(value) is bytes:
        return Serialized(META_RAW, [value], [])
    _ctx.contained_refs = []
    try:
        buffers: list[pickle.PickleBuffer] = []
        import io

        stream = io.BytesIO()
        pickler = _Pickler(stream, protocol=5, buffer_callback=buffers.append)
        pickler.dump(value)
        payload = stream.getvalue()
        raw_buffers = [payload] + [b.raw() for b in buffers]
        return Serialized(META_PICKLE5, raw_buffers, list(_ctx.contained_refs))
    finally:
        _ctx.contained_refs = None


def serialize(value: Any) -> tuple[bytes, bytes, list]:
    """Serialize ``value`` → (metadata, blob, contained_object_refs)."""
    s = serialize_value(value)
    return s.metadata, s.to_blob(), s.contained


def serialize_error(error) -> tuple[bytes, bytes, list]:
    payload = cloudpickle.dumps(error)
    return META_ERROR, _frame([payload]), []


def deserialize(metadata: bytes, blob: bytes | memoryview) -> Any:
    if metadata == META_RAW:
        return bytes(blob)
    bufs = _unframe(blob)
    if metadata == META_ERROR:
        # Return (not raise) so callers can re-raise with the cause's type
        # (RayTaskError.as_instanceof_cause, reference exceptions.py).
        error = pickle.loads(bufs[0])
        return error if isinstance(error, BaseException) else RuntimeError(str(error))
    if metadata in (META_PICKLE5, META_ACTOR_HANDLE):
        return pickle.loads(bufs[0], buffers=[pickle.PickleBuffer(b) for b in bufs[1:]])
    raise ValueError(f"Unknown object metadata: {metadata!r}")


def _frame_layout(buffers: list) -> tuple[list[tuple[int, int]], int]:
    """(offset, size) per buffer + total framed size."""
    n = len(buffers)
    table_end = _HEADER.size + n * _ENTRY.size
    entries = []
    offset = _pad(table_end)
    for buf in buffers:
        offset = _pad(offset)
        size = memoryview(buf).nbytes
        entries.append((offset, size))
        offset += size
    return entries, offset


def framed_size(buffers: list) -> int:
    return _frame_layout(buffers)[1]


def frame_into(view: memoryview, buffers: list) -> int:
    """Write the frame (header + aligned buffers) into ``view``; returns
    total bytes written. ``view`` must hold ``framed_size(buffers)``."""
    entries, total = _frame_layout(buffers)
    n = len(buffers)
    table_end = _HEADER.size + n * _ENTRY.size
    header = _HEADER.pack(_MAGIC, n) + b"".join(
        _ENTRY.pack(o, s) for o, s in entries)
    view[: len(header)] = header
    pos = len(header)
    for (offset, size), buf in zip(entries, buffers):
        if offset != pos:
            view[pos:offset] = b"\x00" * (offset - pos)
        view[offset : offset + size] = memoryview(buf).cast("B")
        pos = offset + size
    return total


def _frame(buffers: list) -> bytes:
    out = bytearray(framed_size(buffers))
    frame_into(memoryview(out), buffers)
    return bytes(out)


def _unframe(blob: bytes | memoryview) -> list[memoryview]:
    if not _HAS_PEP688 and isinstance(blob, PlasmaBuffer):
        # memoryview(PlasmaBuffer) needs PEP-688 (__buffer__, 3.12+); on
        # older interpreters copy out and release the read pin eagerly.
        blob = blob.copy_and_release()
    mv = memoryview(blob)
    magic, n = _HEADER.unpack_from(mv, 0)
    if magic != _MAGIC:
        raise ValueError("Corrupt object blob (bad magic)")
    bufs = []
    pos = _HEADER.size
    for _ in range(n):
        offset, size = _ENTRY.unpack_from(mv, pos)
        pos += _ENTRY.size
        bufs.append(mv[offset : offset + size])
    return bufs


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN
