"""Streaming generators: tasks that yield results before they finish.

Equivalent of the reference's ``ObjectRefGenerator`` / streaming-generator
protocol (``python/ray/_raylet.pyx:294`` ObjectRefGenerator;
``src/ray/core_worker/task_manager.h:212`` owner-side streaming refs):

  * A task or actor method declared ``num_returns="streaming"`` must return
    a (sync or async) generator. The executor reports each yielded item to
    the owner the moment it is produced — inline for small values, via the
    shm store for large ones — so consumers read results while the task is
    still running.
  * Item object IDs are the deterministic task-return IDs
    (``ObjectID.for_task_return(task_id, index+1)``), so a retried
    generator regenerates the same refs and reports are idempotent.
  * Backpressure: with ``_generator_backpressure_num_objects=N`` the
    executor pauses once N reported items are unconsumed, long-polling the
    owner (``WaitGeneratorConsumed``) until the consumer catches up —
    the reference's generator pause/resume protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from .ids import ObjectID, TaskID
from .status import GetTimeoutError


class StreamState:
    """Owner-side state of one executing streaming generator
    (reference ``task_manager.h`` ObjectRefStream)."""

    def __init__(self, task_id: bytes):
        self.task_id = task_id
        self.cond = threading.Condition()
        self.num_items = 0          # high-water mark of reported items
        self.consumed = 0           # items handed to the consumer
        self.finished = False
        self.total: int | None = None
        self.error: Exception | None = None
        # Producer backpressure long-polls park asyncio futures here instead
        # of blocking an executor thread: (until, loop, future).
        self._async_waiters: list[tuple[int, Any, Any]] = []
        # Consumer-side async item waits: (cursor, loop, future) fired when
        # item `cursor` is reported (or the stream ends) — lets async
        # consumers (Serve proxy) wait loop-natively, no thread per stream.
        self._item_waiters: list[tuple[int, Any, Any]] = []

    def _fire_async_waiters_locked(self) -> None:
        remaining = []
        for until, loop, fut in self._async_waiters:
            if self.consumed >= until or self.error is not None or self.finished:
                loop.call_soon_threadsafe(lambda f=fut: f.done() or f.set_result(True))
            else:
                remaining.append((until, loop, fut))
        self._async_waiters = remaining

    def add_async_waiter(self, until: int, loop, fut) -> bool:
        """Register a loop-native waiter for ``consumed >= until``.
        Returns False if the condition already holds (no wait needed)."""
        with self.cond:
            if self.consumed >= until or self.error is not None or self.finished:
                return False
            self._async_waiters.append((until, loop, fut))
            return True

    def _fire_item_waiters_locked(self) -> None:
        remaining = []
        for cursor, loop, fut in self._item_waiters:
            if cursor < self.num_items or self.finished:
                loop.call_soon_threadsafe(lambda f=fut: f.done() or f.set_result(True))
            else:
                remaining.append((cursor, loop, fut))
        self._item_waiters = remaining

    def add_item_waiter(self, cursor: int, loop, fut) -> bool:
        """Register a loop-native waiter for item ``cursor`` (or stream
        end). Returns False if it is already available."""
        with self.cond:
            if cursor < self.num_items or self.finished:
                return False
            self._item_waiters.append((cursor, loop, fut))
            return True

    def report_item(self, index: int) -> None:
        with self.cond:
            if index + 1 > self.num_items:
                self.num_items = index + 1
            self.cond.notify_all()
            self._fire_item_waiters_locked()

    def finish(self, total: int) -> None:
        with self.cond:
            self.finished = True
            if self.total is None or total > self.total:
                self.total = total
            if self.total > self.num_items:
                self.num_items = self.total
            self.cond.notify_all()
            self._fire_async_waiters_locked()
            self._fire_item_waiters_locked()

    def fail(self, error: Exception) -> None:
        with self.cond:
            if self.error is None:
                self.error = error
            self.finished = True
            self.cond.notify_all()
            self._fire_async_waiters_locked()
            self._fire_item_waiters_locked()

    def mark_consumed(self) -> int:
        with self.cond:
            self.consumed += 1
            self.cond.notify_all()
            self._fire_async_waiters_locked()
            return self.consumed


class ObjectRefGenerator:
    """User-facing handle over a streaming task: iterating yields
    ``ObjectRef``s in yield order, blocking until the next item has been
    reported (reference ``ObjectRefGenerator``, ``_raylet.pyx:294``)."""

    def __init__(self, worker, stream: StreamState, owner_address: str):
        self._worker = worker
        self._stream = stream
        self._owner_address = owner_address
        self._cursor = 0
        self._released = False

    @property
    def task_id(self) -> bytes:
        return self._stream.task_id

    def __iter__(self):
        return self

    def __next__(self):
        return self._next_sync(timeout=None)

    def _next_sync(self, timeout: float | None):
        from .object_ref import ObjectRef

        stream = self._stream
        deadline = None if timeout is None else time.monotonic() + timeout
        with stream.cond:
            while self._cursor >= stream.num_items and not stream.finished:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"Timed out waiting for streaming item {self._cursor}"
                    )
                stream.cond.wait(remaining)
            error = stream.error
        if self._cursor >= stream.num_items:
            # Exhausted (or failed): drop owner-side stream state.
            self._release()
            if error is not None:
                raise error
            raise StopIteration
        index = self._cursor
        self._cursor += 1
        stream.mark_consumed()
        oid = ObjectID.for_task_return(TaskID(stream.task_id), index + 1)
        return ObjectRef(oid, self._owner_address)

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        loop = asyncio.get_running_loop()
        # Loop-native wait for the next item: no executor thread is parked
        # per waiting stream (matters with many concurrent token streams).
        fut = loop.create_future()
        if self._stream.add_item_waiter(self._cursor, loop, fut):
            await fut
        try:
            # Item (or end) is available: _next_sync returns without blocking.
            return self._next_sync(timeout=30.0)
        except StopIteration:
            raise StopAsyncIteration

    def completed(self) -> bool:
        with self._stream.cond:
            return self._stream.finished

    def _release(self) -> None:
        if self._released:
            return
        self._released = True
        try:
            self._worker.release_stream(self._stream.task_id)
        except Exception:
            pass  # interpreter shutdown / worker already gone

    def close(self) -> None:
        """Abandon the stream: the producer is cancelled at its next report
        (reference: generator cancellation on consumer release)."""
        self._release()

    def __del__(self):
        self._release()

    def __repr__(self):
        return f"ObjectRefGenerator(task={self._stream.task_id.hex()[:12]}, cursor={self._cursor})"
