"""RPC layer: asyncio TCP transport with msgpack framing.

TPU-native equivalent of the reference's ``src/ray/rpc/`` (gRPC server/client
wrappers). The control plane does not need gRPC/protobuf machinery on TPU
VMs; a length-prefixed msgpack protocol over asyncio TCP gives the same
request/response semantics with far less code:

    frame   := [u32 little-endian length][msgpack body]
    request := {"id": u64, "method": str, "payload": {...}}
    reply   := {"id": u64, "ok": bool, "payload": {...} | "error": str}

``RetryableRpcClient`` mirrors ``retryable_grpc_client.h`` (exponential
backoff, bounded retries, fail-fast on server-declared death).
``RpcChaos`` mirrors ``rpc_chaos.h:23-37``: deterministic failure injection
per method, configured via the ``testing_rpc_failure`` config entry /
``RAY_TPU_testing_rpc_failure`` env var.
"""

from __future__ import annotations

import asyncio
import logging
import random
import struct
import threading
from typing import Any, Awaitable, Callable

import msgpack

from .config import get_config
from .status import RpcError

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 31

# Strong roots for fire-and-forget asyncio tasks. The event loop holds only
# weak references to tasks; a task blocked on an RPC future forms a
# reference cycle (task -> coroutine frame -> client -> pending future ->
# task) with no external root, so the cyclic GC can destroy it mid-await,
# throwing GeneratorExit into the coroutine. Every background task must be
# anchored here until done.
_BACKGROUND_TASKS: set = set()


def spawn(coro) -> "asyncio.Task":
    """ensure_future with a strong reference for the task's lifetime."""
    task = asyncio.ensure_future(coro)
    _BACKGROUND_TASKS.add(task)
    task.add_done_callback(_BACKGROUND_TASKS.discard)
    return task


def _pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"Frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


_injections_counter = None


def record_chaos_injection(kind: str, method: str) -> None:
    """Count one injected fault in ``ray_tpu_chaos_injections_total``
    (lazily created so chaos-free processes never start the metrics
    flusher). Never raises: chaos accounting must not become a fault."""
    global _injections_counter
    try:
        if _injections_counter is None:
            from ..util.metrics import Counter

            _injections_counter = Counter(
                "ray_tpu_chaos_injections_total",
                "Injected chaos faults by kind and RPC method",
                tag_keys=("kind", "method"))
        _injections_counter.inc(tags={"kind": kind, "method": method or ""})
    except Exception:
        pass


class RpcChaos:
    """Request/response fault injection (rpc_chaos.h:23-37), extended with
    delay injection, a deterministic every-Nth mode, and seeded
    probabilistic modes.

    Spec grammar (``testing_rpc_failure`` config / env var), one rule per
    ``;``-separated item::

        Method=req_prob,resp_prob              # legacy positional form
        Method=req_prob,resp_prob,delay_ms     # legacy + delay
        Method=req:0.2,resp:0.1,client:0.3,nth:3,delay:50,max:10

    ``nth`` makes matched injections deterministic (every Nth call of
    that side, no RNG); ``max`` caps total injections for the rule;
    ``delay`` (ms) is applied to every matched request. ``Method`` may be
    ``*`` to match all methods. Subclasses (``chaos.plan.PlanChaos``)
    override the decision hooks to drive pre-compiled fault schedules and
    the non-RPC fault kinds (worker kills, spill errors, partitions).
    """

    def __init__(self, spec: str = "", seed: int | None = None):
        self._rules = self._parse_spec(spec)
        if seed is None:
            seed = get_config().testing_rpc_failure_seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._calls: dict[tuple[str, str], int] = {}
        # (kind, method) -> injections fired (mirrors the metric; used by
        # `cli doctor` / the chaos report without a GCS round trip).
        self.injections_total: dict[tuple[str, str], int] = {}

    @staticmethod
    def _parse_spec(spec: str) -> dict[str, dict]:
        rules: dict[str, dict] = {}
        for item in filter(None, (spec or "").split(";")):
            method, _, clauses = item.partition("=")
            rule = {"request": 0.0, "response": 0.0, "client": 0.0,
                    "nth": 0, "delay_ms": 0.0, "max": 0, "injected": 0}
            parts = [c.strip() for c in clauses.split(",") if c.strip()]
            if parts and ":" not in parts[0]:
                # Legacy positional: req_prob, resp_prob [, delay_ms]
                rule["request"] = float(parts[0])
                if len(parts) > 1:
                    rule["response"] = float(parts[1])
                if len(parts) > 2:
                    rule["delay_ms"] = float(parts[2])
            else:
                for clause in parts:
                    key, _, value = clause.partition(":")
                    if key == "req":
                        rule["request"] = float(value)
                    elif key == "resp":
                        rule["response"] = float(value)
                    elif key == "client":
                        rule["client"] = float(value)
                    elif key == "nth":
                        rule["nth"] = int(value)
                    elif key in ("delay", "delay_ms"):
                        rule["delay_ms"] = float(value)
                    elif key in ("max", "count"):
                        rule["max"] = int(value)
                    else:
                        raise ValueError(f"Unknown chaos clause {clause!r}")
            rules[method.strip()] = rule
        return rules

    def _rule_for(self, method: str) -> dict | None:
        return self._rules.get(method) or self._rules.get("*")

    def _decide(self, method: str, where: str) -> bool:
        rule = self._rule_for(method)
        if rule is None:
            return False
        prob = rule[where]
        sided = rule["request"] or rule["response"] or rule["client"]
        with self._lock:
            if rule["max"] and rule["injected"] >= rule["max"]:
                return False
            if rule["nth"]:
                # Deterministic mode: fire on every Nth call of this side.
                # With no side probabilities given, nth applies to requests.
                if sided and not prob:
                    return False
                if not sided and where != "request":
                    return False
                key = (method, where)
                n = self._calls.get(key, 0) + 1
                self._calls[key] = n
                hit = n % rule["nth"] == 0
            else:
                if not prob:
                    return False
                hit = self._rng.random() < prob
            if hit:
                rule["injected"] += 1
        if hit:
            self.record_injection(f"rpc_{where}_drop", method)
        return hit

    def record_injection(self, kind: str, method: str = "") -> None:
        with self._lock:
            key = (kind, method)
            self.injections_total[key] = self.injections_total.get(key, 0) + 1
        record_chaos_injection(kind, method)

    # -- decision hooks (all consulted from hot paths: fast no-op when no
    # matching rule exists) ------------------------------------------------
    def should_fail_request(self, method: str, tag: str = "") -> bool:
        return self._decide(method, "request")

    def should_fail_response(self, method: str, tag: str = "") -> bool:
        return self._decide(method, "response")

    def should_drop_client_send(self, method: str) -> bool:
        return self._decide(method, "client")

    def request_delay_s(self, method: str, tag: str = "") -> float:
        rule = self._rule_for(method)
        if rule is None or not rule["delay_ms"]:
            return 0.0
        self.record_injection("rpc_delay", method)
        return rule["delay_ms"] / 1000.0

    def peer_blocked(self, address: str) -> bool:
        """Node-pair partition / endpoint blackout probe (plan-driven)."""
        return False

    def take_kill_on_lease(self, node_id: str = "") -> bool:
        """Raylet asks: kill the worker of the lease just granted?"""
        return False

    def take_kill_loop_tick(self) -> bool:
        """A compiled-loop stage executor asks, once per tick: die here
        (between consuming inputs and producing output)?"""
        return False

    def take_preempt_slice(self, node_id: str = "") -> bool:
        """A raylet asks, once per heartbeat tick: does a GCE-style
        preemption notice land on this node now? (plan-driven)"""
        return False

    def maybe_fail_spill(self) -> bool:
        """Raylet asks: fail this spill-file disk write?"""
        return False

    def maybe_fail_store_create(self) -> bool:
        """Object store asks: fail this arena allocation (as store-full)?"""
        return False


_chaos: RpcChaos | None = None


def get_chaos() -> RpcChaos:
    global _chaos
    if _chaos is None:
        _chaos = RpcChaos(get_config().testing_rpc_failure)
    return _chaos


def set_chaos(chaos: RpcChaos | None) -> None:
    """Install failure injection for this process (tests)."""
    global _chaos
    _chaos = chaos


Handler = Callable[[dict], Awaitable[dict]]


class RpcServer:
    """Asyncio TCP server dispatching named methods (grpc_server.h equiv)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, tag: str = ""):
        self.host = host
        self.port = port
        # Chaos tag naming the service this server fronts ("gcs",
        # "raylet", ...) so plans can target a component, not a method.
        self.tag = tag
        self._handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def register_service(self, service: object, prefix: str = "") -> None:
        """Register every ``handle_<Name>`` coroutine as method ``<Name>``."""
        for attr in dir(service):
            if attr.startswith("handle_"):
                self.register(prefix + attr[len("handle_") :], getattr(service, attr))

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.close()
            # wait_closed() (3.12) blocks until every connection handler
            # finishes; give in-flight RPCs a grace period, then abort the
            # stragglers (long-polls would otherwise hold shutdown forever).
            if grace > 0:
                try:
                    await asyncio.wait_for(self._server.wait_closed(), timeout=grace)
                except Exception:
                    pass
            for writer in list(self._conns):
                try:
                    writer.transport.abort()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except Exception:  # pragma: no cover - teardown best effort
                pass
            self._server = None

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        try:
            while True:
                msg = await _read_frame(reader)
                spawn(self._dispatch(msg, writer))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg: dict, writer: asyncio.StreamWriter) -> None:
        method = msg.get("method", "")
        chaos = get_chaos()
        if chaos.should_fail_request(method, tag=self.tag):
            return  # drop request silently
        delay = chaos.request_delay_s(method, tag=self.tag)
        if delay > 0:
            await asyncio.sleep(delay)
        handler = self._handlers.get(method)
        if handler is None:
            reply = {"id": msg["id"], "ok": False, "error": f"No such method: {method}"}
        else:
            try:
                payload = await handler(msg.get("payload") or {})
                reply = {"id": msg["id"], "ok": True, "payload": payload}
            except Exception as e:
                logger.debug("RPC handler %s raised", method, exc_info=True)
                reply = {"id": msg["id"], "ok": False, "error": f"{type(e).__name__}: {e}"}
        if chaos.should_fail_response(method, tag=self.tag):
            return  # drop response
        try:
            writer.write(_pack(reply))
            await writer.drain()
        except (ConnectionError, RuntimeError):
            pass


class RpcClient:
    """Single-connection async client (grpc_client.h equiv)."""

    def __init__(self, address: str):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._lock = asyncio.Lock()
        self._read_task: asyncio.Task | None = None

    async def _ensure_connected(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        async with self._lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            cfg = get_config()
            try:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self._host, self._port),
                    timeout=cfg.rpc_connect_timeout_s,
                )
            except (OSError, asyncio.TimeoutError) as e:
                # Normalize so every transport failure surfaces as RpcError
                # (callers' except clauses and the retry filter rely on it).
                err = RpcError(f"Connection to {self.address} failed: {e}")
                err.undelivered = True  # request never reached the server
                raise err from e
            self._read_task = spawn(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await _read_frame(self._reader)
                fut = self._pending.pop(msg["id"], None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError, RpcError) as e:
            self._fail_all(RpcError(f"Connection to {self.address} lost: {e}"))

    def _fail_all(self, error: Exception) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None
        self._reader = None
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(error)

    async def call(self, method: str, payload: dict | None = None, timeout: float | None = None) -> dict:
        chaos = get_chaos()
        if chaos.peer_blocked(self.address):
            # Partition / endpoint blackout: behaves exactly like an
            # unreachable host, so retry & failover paths see the real
            # failure mode (RetryableRpcClient retries these).
            err = RpcError(f"Connection to {self.address} failed: "
                           "chaos-injected partition")
            err.undelivered = True
            raise err
        if chaos.should_drop_client_send(method):
            err = RpcError(f"Connection to {self.address} failed: "
                           f"chaos-injected client drop of {method}")
            err.undelivered = True
            raise err
        await self._ensure_connected()
        self._next_id += 1
        req_id = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            self._writer.write(_pack({"id": req_id, "method": method, "payload": payload or {}}))
            await self._writer.drain()
        except (ConnectionError, RuntimeError) as e:
            self._pending.pop(req_id, None)
            self._fail_all(RpcError(str(e)))
            raise RpcError(f"Send to {self.address} failed: {e}") from e
        try:
            msg = await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            raise RpcError(f"RPC {method} to {self.address} timed out")
        if not msg.get("ok"):
            raise RpcError(msg.get("error", "unknown RPC error"))
        return msg.get("payload") or {}

    async def close(self) -> None:
        if self._read_task is not None:
            self._read_task.cancel()
        self._fail_all(RpcError("client closed"))


class RetryableRpcClient(RpcClient):
    """Client with exponential-backoff reconnect (retryable_grpc_client.h)."""

    async def call(self, method: str, payload: dict | None = None, timeout: float | None = None) -> dict:
        cfg = get_config()
        base = cfg.rpc_retry_base_delay_ms / 1000.0
        cap = cfg.rpc_retry_max_delay_ms / 1000.0
        delay = base
        last: Exception | None = None
        for attempt in range(cfg.rpc_max_retries + 1):
            try:
                return await super().call(method, payload, timeout)
            except RpcError as e:
                msg = str(e)
                if "No such method" in msg or msg.startswith("RPC") and "timed out" in msg:
                    raise
                # Application-level errors (handler raised) are not retryable;
                # only transport failures are.
                if "Connection" not in msg and "Send to" not in msg and "refused" not in msg.lower():
                    raise
                last = e
                if attempt == cfg.rpc_max_retries:
                    break
                if cfg.rpc_retry_jitter:
                    # Full jitter: U(0, min(cap, base*2^attempt)). Bare
                    # doubling synchronizes every client that failed at the
                    # same instant into retry waves (mass failure under
                    # chaos); sampling the whole window decorrelates them.
                    await asyncio.sleep(random.uniform(
                        0.0, min(cap, base * (2 ** attempt))))
                else:
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, cap)
        raise RpcError(f"RPC {method} to {self.address} failed after retries: {last}")


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    Plays the role of the CoreWorker's io_service threads
    (``core_worker_process.h``): synchronous frontend code schedules
    coroutines here and blocks on concurrent futures.
    """

    def __init__(self, name: str = "raytpu-io"):
        self.loop = asyncio.new_event_loop()
        self._inflight: set = set()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run_coro(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        # Anchor the future (and through its cancel-chaining callback, the
        # task) so fire-and-forget coroutines can't be GC'd mid-await.
        self._inflight.add(fut)
        fut.add_done_callback(self._inflight.discard)
        return fut

    def run_sync(self, coro, timeout: float | None = None):
        return self.run_coro(coro).result(timeout)

    def stop(self) -> None:
        async def _drain():
            me = asyncio.current_task()
            tasks = [t for t in asyncio.all_tasks() if t is not me]
            for t in tasks:
                t.cancel()
            # Let cancellations unwind (finally blocks) before the loop dies,
            # so no "Task was destroyed but it is pending!" floods.
            if tasks:
                await asyncio.wait(tasks, timeout=2.0)

        if self.loop.is_running():
            try:
                asyncio.run_coroutine_threadsafe(_drain(), self.loop).result(timeout=4)
            except Exception:
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=5)
        if not self.loop.is_running():
            try:
                self.loop.close()
            except Exception:
                pass
