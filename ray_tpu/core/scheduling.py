"""Scheduling policies: node selection for tasks, actors, placement groups.

Equivalent of the reference's ``src/ray/raylet/scheduling/policy/``:

  * hybrid (default)   — pack onto the best already-utilized feasible node
                         until its score exceeds the spread threshold, then
                         prefer the least-utilized (hybrid_scheduling_policy.cc)
  * spread             — round-robin over feasible nodes
  * node-affinity      — pin to a node (soft/hard)
  * node-label         — filter by labels then hybrid
  * placement-group bundles — PACK / SPREAD / STRICT_PACK / STRICT_SPREAD
                         (bundle_scheduling_policy.cc)

Node views are the GCS node table dicts: {node_id, resources: {total,
available, labels}, state}.
"""

from __future__ import annotations

import random

from .config import get_config
from .resources import NodeResources, ResourceSet

_spread_counter = 0


def _feasible(nodes: dict, request: ResourceSet, labels: dict | None = None) -> list[tuple[str, NodeResources]]:
    out = []
    for node_id, node in nodes.items():
        if node.get("state") != "ALIVE" or node.get("draining"):
            # Draining nodes (preemption notice) are capacity that is
            # about to vanish — never schedule new work onto them.
            continue
        nr = NodeResources.from_dict(node["resources"])
        if labels and not all(nr.labels.get(k) == v for k, v in labels.items()):
            continue
        if request.subset_of(nr.total):
            out.append((node_id, nr))
    return out


def select_node_for_resources(nodes: dict, resources: dict, strategy: dict) -> str | None:
    """Pick a node for one task/actor. Returns node_id hex or None."""
    request = ResourceSet(resources)
    kind = strategy.get("type", "hybrid")

    if kind == "node_affinity":
        target = strategy["node_id"]
        node = nodes.get(target)
        if node and node.get("state") == "ALIVE":
            nr = NodeResources.from_dict(node["resources"])
            if request.subset_of(nr.total):
                return target
        if strategy.get("soft"):
            kind = "hybrid"
        else:
            return None

    labels = strategy.get("labels") or {}
    feasible = _feasible(nodes, request, labels)
    if not feasible:
        return None
    available = [(nid, nr) for nid, nr in feasible if nr.can_fit(request)]

    if kind == "spread":
        global _spread_counter
        pool = available or feasible
        _spread_counter += 1
        return pool[_spread_counter % len(pool)][0]

    # hybrid: among nodes with capacity, prefer the highest-utilization node
    # whose score stays under the threshold (pack); otherwise least utilized
    # (spread). Reference: hybrid_scheduling_policy.cc.
    threshold = get_config().scheduler_spread_threshold
    if available:
        under = [(nid, nr) for nid, nr in available if nr.utilization() < threshold]
        if under:
            return max(under, key=lambda x: (x[1].utilization(), x[0]))[0]
        return min(available, key=lambda x: (x[1].utilization(), x[0]))[0]
    # No capacity now but feasible: queue on the least loaded feasible node.
    return min(feasible, key=lambda x: (x[1].utilization(), x[0]))[0]


def schedule_placement_group(
    nodes: dict, bundles: list[dict], strategy: str, use_total: bool = False
) -> list[str] | None:
    """Map each bundle to a node id. Returns per-bundle node list or None.

    ``use_total=True`` checks against node TOTAL resources (feasibility:
    could this ever be placed on an empty cluster?) rather than currently
    available ones. Reference: bundle_scheduling_policy.cc.
    """
    alive = {
        nid: NodeResources.from_dict(n["resources"])
        for nid, n in nodes.items()
        if n.get("state") == "ALIVE" and not n.get("draining")
    }
    if use_total:
        for nr in alive.values():
            # acquire() rebinds `available` rather than mutating, so sharing
            # the total ResourceSet here is safe.
            nr.available = nr.total
    if not alive:
        return None
    requests = [ResourceSet(b) for b in bundles]

    if strategy == "STRICT_PACK":
        # All bundles on one node (e.g. one TPU slice host group).
        total = ResourceSet()
        for r in requests:
            total = total.add(r)
        candidates = [nid for nid, nr in alive.items() if nr.can_fit(total)]
        if not candidates:
            return None
        return [candidates[0]] * len(bundles)

    if strategy == "STRICT_SPREAD":
        placement: list[str] = []
        used: set[str] = set()
        for r in requests:
            pick = None
            for nid, nr in sorted(alive.items(), key=lambda x: x[1].utilization()):
                if nid not in used and nr.can_fit(r):
                    pick = nid
                    break
            if pick is None:
                return None
            used.add(pick)
            alive[pick].acquire(r)
            placement.append(pick)
        return placement

    # PACK (best effort pack) / SPREAD (best effort spread).
    placement = []
    order = sorted(alive.items(), key=lambda x: x[1].utilization(), reverse=(strategy == "PACK"))
    for r in requests:
        pick = None
        nodes_sorted = sorted(
            alive.items(),
            key=lambda x: x[1].utilization(),
            reverse=(strategy == "PACK"),
        )
        for nid, nr in nodes_sorted:
            if nr.can_fit(r):
                pick = nid
                break
        if pick is None:
            return None
        alive[pick].acquire(r)
        placement.append(pick)
    return placement
