"""ObjectRef: the user-facing distributed future.

Equivalent of the reference's ``ObjectRef`` (``python/ray/includes/object_ref.pxi``):
wraps an :class:`ObjectID` plus the owner's RPC address. Python refcount
integrates with the distributed ``ReferenceCounter`` — ``__del__`` removes a
local ref, and pickling inside task args / ``ray.put`` records containment
(borrowing, reference ``reference_count.h:66``).
"""

from __future__ import annotations

from . import serialization
from .ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_skip_refcount", "_callsite", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "", *, _add_local_ref: bool = True):
        self._id = object_id
        self._owner_address = owner_address
        self._skip_refcount = not _add_local_ref
        if _add_local_ref:
            # Creation callsite for `ray memory`-style reference debugging
            # (observability/memory.py; reference record_ref_creation_sites):
            # the first user frame above the ray_tpu call that made the ref.
            from ..observability.memory import capture_callsite

            self._callsite = capture_callsite()
            _refcounter_hook("add_local", self)
        else:
            self._callsite = ""

    @property
    def callsite(self) -> str:
        return self._callsite

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    @property
    def owner_address(self) -> str:
        return self._owner_address

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        if not self._skip_refcount:
            try:
                _refcounter_hook("remove_local", self)
            except Exception:
                pass

    # Support `ray.get(ref)` style plus direct await in async actors.
    def __await__(self):
        from . import worker as worker_mod

        def _get():
            return worker_mod.global_worker().get([self])[0]

        import concurrent.futures

        loop_result = yield from _run_in_thread(_get).__await__()
        return loop_result


async def _run_in_thread(fn):
    import asyncio

    return await asyncio.get_running_loop().run_in_executor(None, fn)


_hooks = {}


def _refcounter_hook(kind: str, ref: ObjectRef) -> None:
    hook = _hooks.get(kind)
    if hook is not None:
        hook(ref)


def install_refcount_hooks(add_local, remove_local) -> None:
    _hooks["add_local"] = add_local
    _hooks["remove_local"] = remove_local


def clear_refcount_hooks() -> None:
    _hooks.clear()


def _reconstruct_ref(id_binary: bytes, owner_address: str) -> ObjectRef:
    """Unpickle an ObjectRef: registers a local ref in the deserializing
    worker (the borrower) — the borrowing entry point."""
    return ObjectRef(ObjectID(id_binary), owner_address)


def _reduce_object_ref(ref: ObjectRef):
    return _reconstruct_ref, (ref.binary(), ref.owner_address)


serialization.register_object_ref_serializer(ObjectRef, _reduce_object_ref)
