"""In-process memory store for small objects and pending futures.

Equivalent of the reference's ``CoreWorkerMemoryStore``
(``src/ray/core_worker/store_provider/memory_store/``): small task returns
and inlined values live here; ``get`` blocks on a threading event until the
value arrives (task completion) or a timeout fires. Error objects are stored
like values and re-raised on deserialization.
"""

from __future__ import annotations

import threading
from typing import Iterable

from .ids import ObjectID


class _Entry:
    __slots__ = ("metadata", "blob", "in_plasma", "node_id")

    def __init__(self, metadata: bytes, blob: bytes, in_plasma: bool = False, node_id: bytes | None = None):
        self.metadata = metadata
        self.blob = blob
        self.in_plasma = in_plasma
        self.node_id = node_id


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: dict[ObjectID, _Entry] = {}
        self._waiters: dict[ObjectID, list[threading.Event]] = {}
        # Event-driven (non-blocking) waiters: oid -> list of callbacks fired
        # once, from the putting thread, when the object becomes present.
        self._callbacks: dict[ObjectID, list] = {}

    def _store(self, object_id: ObjectID, entry: _Entry) -> None:
        with self._lock:
            self._objects[object_id] = entry
            events = self._waiters.pop(object_id, [])
            callbacks = self._callbacks.pop(object_id, [])
        for ev in events:
            ev.set()
        for cb in callbacks:
            try:
                cb(object_id)
            except Exception:
                pass

    def put(self, object_id: ObjectID, metadata: bytes, blob: bytes) -> None:
        self._store(object_id, _Entry(metadata, blob))

    def put_plasma_marker(self, object_id: ObjectID, node_id: bytes) -> None:
        """Record that the value lives in plasma on ``node_id`` (the
        reference stores an IN_PLASMA_ERROR sentinel the same way)."""
        self._store(object_id, _Entry(b"", b"", in_plasma=True, node_id=node_id))

    def add_callback(self, object_id: ObjectID, callback) -> bool:
        """Register ``callback(oid)`` for when ``object_id`` appears.
        Returns False (callback NOT registered) if it is already present."""
        with self._lock:
            if object_id in self._objects:
                return False
            self._callbacks.setdefault(object_id, []).append(callback)
            return True

    def remove_callback(self, object_id: ObjectID, callback) -> None:
        with self._lock:
            cbs = self._callbacks.get(object_id)
            if cbs is not None:
                try:
                    cbs.remove(callback)
                except ValueError:
                    pass
                if not cbs:
                    self._callbacks.pop(object_id, None)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID) -> _Entry | None:
        with self._lock:
            return self._objects.get(object_id)

    def wait_ready(self, object_ids: Iterable[ObjectID], num_returns: int, timeout: float | None) -> tuple[list[ObjectID], list[ObjectID]]:
        """Block until ``num_returns`` of ``object_ids`` are present."""
        object_ids = list(object_ids)
        ev = threading.Event()
        with self._lock:
            ready = [oid for oid in object_ids if oid in self._objects]
            if len(ready) >= num_returns:
                return ready[:num_returns], [o for o in object_ids if o not in ready[:num_returns]]
            for oid in object_ids:
                if oid not in self._objects:
                    self._waiters.setdefault(oid, []).append(ev)
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            fired = ev.wait(remaining)
            ev.clear()
            with self._lock:
                ready = [oid for oid in object_ids if oid in self._objects]
                if len(ready) >= num_returns or not fired:
                    ready = ready[:max(len(ready), 0)]
                    ready_set = set(ready[:num_returns]) if len(ready) >= num_returns else set(ready)
                    return (
                        [o for o in object_ids if o in ready_set],
                        [o for o in object_ids if o not in ready_set],
                    )
                for oid in object_ids:
                    if oid not in self._objects:
                        self._waiters.setdefault(oid, []).append(ev)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
