"""GCS — the cluster-wide control plane.

Equivalent of the reference's ``GcsServer`` (``src/ray/gcs/gcs_server/
gcs_server.h:89``) composed of the same managers:

  * NodeManager        — registration, resource views, death broadcast
  * ActorManager       — actor registration/creation/restart FSM
                         (``gcs_actor_manager.h:324``, RestartActor .cc:565)
  * JobManager         — job table
  * InternalKV         — cluster KV (function table, named things)
  * Publisher          — long-poll pub/sub (``src/ray/pubsub/publisher.h:300``)
  * HealthCheckManager — periodic raylet pings (``gcs_health_check_manager.h:61``)

Storage defaults to in-memory (the reference's ``InMemoryStoreClient``);
with ``gcs_storage_backend=file`` the durable tables snapshot to disk
(``gcs_storage.py``) and a restarted GCS recovers them — the raylets
re-register on heartbeat, standing in for the reference's Redis-backed
fault tolerance (``redis_store_client.h:107``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from .config import get_config
from .ids import ActorID, NodeID
from .rpc import RetryableRpcClient, RpcClient, RpcServer, spawn
from ..chaos import clock as chaos_clock

logger = logging.getLogger(__name__)

# Actor FSM states (reference rpc::ActorTableData::ActorState).
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class Publisher:
    """Per-channel sequenced message log with long-poll subscribers.

    Fan-out is BATCHED (reference ``pubsub/publisher.h`` buffered
    per-subscriber mailboxes): a publish appends to the channel log and
    schedules ONE deferred wake covering every publish that lands within
    ``gcs_pubsub_batch_window_ms`` — so 1k actor-state churns per flush
    cost one ``notify_all`` instead of 1k, and each woken subscriber
    drains everything past its cursor in one bounded reply
    (``gcs_pubsub_max_batch_msgs`` per channel). Cursor scans are O(new
    messages): sequences are contiguous per channel, so the resume point
    is index arithmetic, not a filter over the whole buffer."""

    def __init__(self, max_buffer: int = 10000):
        self._channels: dict[str, list[tuple[int, Any]]] = {}
        self._seqs: dict[str, int] = {}
        self._cond = asyncio.Condition()
        self._max_buffer = max_buffer
        self._notify_scheduled = False
        # Fan-out evidence (GCS debug_state): wake batching ratio.
        self.publishes_total = 0
        self.notify_batches_total = 0

    async def publish(self, channel: str, message: Any) -> None:
        # Single-loop store: the append is atomic on the event loop; only
        # the wake needs the condition's lock (taken in _notify_waiters).
        seq = self._seqs.get(channel, 0) + 1
        self._seqs[channel] = seq
        buf = self._channels.setdefault(channel, [])
        buf.append((seq, message))
        self.publishes_total += 1
        if len(buf) > self._max_buffer:
            del buf[: len(buf) // 2]
        window_s = get_config().gcs_pubsub_batch_window_ms / 1000.0
        if window_s <= 0:
            await self._notify_waiters()
        elif not self._notify_scheduled:
            self._notify_scheduled = True
            loop = asyncio.get_running_loop()
            loop.call_later(
                window_s,
                lambda: loop.create_task(self._notify_waiters()))

    async def _notify_waiters(self) -> None:
        self._notify_scheduled = False
        self.notify_batches_total += 1
        async with self._cond:
            self._cond.notify_all()

    def current_seq(self, channel: str) -> int:
        return self._seqs.get(channel, 0)

    def _pending(self, cursors: dict[str, int],
                 max_msgs: int) -> dict[str, list]:
        out: dict[str, list] = {}
        for channel, cursor in cursors.items():
            buf = self._channels.get(channel)
            if not buf:
                continue
            # Sequences are contiguous within the buffer: resume index is
            # arithmetic off the head's seq (O(1)), not a full scan.
            start = max(0, cursor - buf[0][0] + 1) if cursor >= buf[0][0] else 0
            if start < len(buf):
                out[channel] = buf[start:start + max_msgs]
        return out

    async def poll(self, cursors: dict[str, int], timeout: float) -> dict[str, list]:
        """Long-poll: block until any channel has messages past its cursor."""
        deadline = time.monotonic() + timeout
        max_msgs = max(1, get_config().gcs_pubsub_max_batch_msgs)
        async with self._cond:
            while True:
                out = self._pending(cursors, max_msgs)
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                try:
                    await asyncio.wait_for(self._cond.wait(), remaining)
                except asyncio.TimeoutError:
                    return {}


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, storage=None,
                 session_dir: str | None = None):
        self._server = RpcServer(host, port, tag="gcs")
        self._server.register_service(self)
        self.publisher = Publisher()
        self._session_dir = session_dir
        # Fault tolerance (redis_store_client.h equivalent): durable tables
        # snapshot through `storage`; a restarted GCS restores them and
        # raylets re-register on their next heartbeat.
        from .gcs_storage import MemoryStorage

        self._storage = storage or MemoryStorage()
        self._last_snapshot: bytes = b""
        self._persist_task: asyncio.Task | None = None
        # Every background coroutine (actor creation, PG scheduling) is
        # tracked so crash()/stop() can cancel them — a "dead" GCS must not
        # keep leasing workers on the shared test event loop (split-brain).
        self._bg_tasks: set[asyncio.Task] = set()
        # node_id(hex) -> {address, resources{total,available,labels}, state,
        #                  last_heartbeat}
        self._nodes: dict[str, dict] = {}
        self._raylet_clients: dict[str, RpcClient] = {}
        # Durable tables ride the sharded store client (one lock per key
        # shard — the reference's store_client/ split) so writes from
        # off-loop ingest threads and the event loop never convoy on one
        # table lock and stay linearizable per key.
        from .store_client import ShardedKv

        shards = get_config().gcs_store_shards
        # actor_id(hex) -> record
        self._actors: ShardedKv = ShardedKv(shards)
        self._named_actors: dict[str, str] = {}  # name -> actor_id hex
        self._jobs: dict[str, dict] = {}
        self._next_job = 1
        self._kv: ShardedKv = ShardedKv(shards)
        self._health_task: asyncio.Task | None = None
        self._placement_groups: dict[str, dict] = {}
        # Observability: task-event ring (gcs_task_manager.h) + per-worker
        # metric snapshots (stats/metric.h aggregation point).
        from .task_events import GcsTaskEventStore
        from ..observability.spans import GcsSpanStore
        from ..util.metrics import Histogram

        # Lease-stage latency histograms, fed at event ingest (submit→lease,
        # queue wait, worker spawn, lease→run). Private (register=False):
        # the GCS often shares a process with a driver whose metrics flusher
        # would otherwise re-report this registry back to us — these are
        # merged into GetMetrics directly via _framework_metrics.
        self._lease_stage_hist = Histogram(
            "ray_tpu_lease_stage_ms",
            "Task lease pipeline stage durations (submit to lease, lease "
            "queue wait, worker spawn/setup, lease to run)",
            tag_keys=("stage", "node_id"), register=False)
        self.task_events = GcsTaskEventStore(
            max_tasks=get_config().task_events_buffer_size,
            on_stage=lambda stage, ms, node: self._lease_stage_hist.observe(
                ms, {"stage": stage, "node_id": (node or "")[:12]}),
        )
        # Trace spans flushed on the task-event path (status SPAN).
        self.span_store = GcsSpanStore(
            max_spans=get_config().span_events_buffer_size)
        # Per-worker memory summaries flushed on the same path (status
        # MEMORY) + the trend histories the leak watcher scans.
        from ..observability.memory import GcsMemoryStore

        self.memory_store = GcsMemoryStore()
        self._memory_watch_task: asyncio.Task | None = None
        # On-demand profiler artifacts registered by raylets (cli profile).
        self._profiles: list[dict] = []
        self._metrics: dict[str, tuple[float, list[dict]]] = {}  # worker -> (ts, snapshot)
        # Error-info table: retained ErrorEvents behind the pub/sub channel
        # (reference ErrorInfoHandler / RAY_ERROR_INFO_CHANNEL).
        self._errors: list[dict] = []
        self._debug_dump_task: asyncio.Task | None = None

    # ------------------------------------------------------------------ util
    def _spawn(self, coro) -> asyncio.Task:
        task = spawn(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    def _cancel_bg(self) -> None:
        if self._health_task:
            self._health_task.cancel()
        if self._persist_task:
            self._persist_task.cancel()
        if self._debug_dump_task:
            self._debug_dump_task.cancel()
        if self._memory_watch_task:
            self._memory_watch_task.cancel()
        for task in list(self._bg_tasks):
            task.cancel()

    async def start(self) -> None:
        self._restore()
        await self._server.start()
        self._health_task = spawn(self._health_check_loop())
        self._persist_task = spawn(self._persist_loop())
        self._memory_watch_task = spawn(self._memory_watch_loop())
        if self._session_dir:
            self._debug_dump_task = spawn(self._debug_dump_loop())

    async def stop(self) -> None:
        self._cancel_bg()
        self._flush()
        await self._server.stop()

    async def crash(self) -> None:
        """Die WITHOUT a final flush — simulates abrupt GCS process death
        for fault-tolerance tests (only snapshots the persist loop already
        wrote survive)."""
        self._cancel_bg()
        await self._server.stop(grace=0.0)

    @property
    def port(self) -> int:
        return int(self.address.rsplit(":", 1)[1])

    # -------------------------------------------------------- fault tolerance
    def _tables(self) -> dict:
        return {
            "kv": self._kv.to_dict(),
            "jobs": self._jobs,
            "next_job": self._next_job,
            "actors": self._actors.to_dict(),
            "named_actors": self._named_actors,
            "placement_groups": self._placement_groups,
        }

    def _flush(self) -> None:
        """Snapshot the durable tables if they changed. Change detection by
        comparing the packed blob — cheaper than instrumenting every
        mutation site and can never miss one."""
        if not self._storage.persistent:
            return
        from .gcs_storage import pack_tables

        try:
            blob = pack_tables(self._tables())
            if blob != self._last_snapshot:
                self._storage.save_blob(blob)
                self._last_snapshot = blob
        except Exception:
            logger.exception("GCS table snapshot failed")

    def _restore(self) -> None:
        tables = self._storage.load()
        if not tables:
            return
        from .store_client import ShardedKv

        shards = get_config().gcs_store_shards
        self._kv = ShardedKv(shards, tables.get("kv", {}))
        self._jobs = tables.get("jobs", {})
        self._next_job = tables.get("next_job", 1)
        self._named_actors = tables.get("named_actors", {})
        self._placement_groups = tables.get("placement_groups", {})
        # Restored ALIVE actors keep their addresses — the processes are
        # still running and clients reconnect transparently. Actors that
        # were mid-creation or mid-restart lost their coroutine with the
        # old GCS; their specs are durable, so creation is re-driven
        # (reference gcs_actor_manager reconstruction on restart).
        self._actors = ShardedKv(shards, tables.get("actors", {}))
        for record in self._actors.values():
            if record["state"] in (PENDING_CREATION, RESTARTING):
                self._spawn(self._create_actor(record))
        for record in self._placement_groups.values():
            if record["state"] == "PENDING":
                self._spawn(self._schedule_pg_loop(record))
        logger.info(
            "GCS restored %d kv keys, %d actors, %d jobs, %d placement groups",
            len(self._kv), len(self._actors), len(self._jobs),
            len(self._placement_groups),
        )

    async def _persist_loop(self) -> None:
        while True:
            await asyncio.sleep(0.2)
            self._flush()

    @property
    def address(self) -> str:
        return self._server.address

    def _raylet(self, node_id_hex: str) -> RpcClient | None:
        node = self._nodes.get(node_id_hex)
        if node is None or node["state"] != "ALIVE":
            return None
        client = self._raylet_clients.get(node_id_hex)
        if client is None:
            client = RetryableRpcClient(node["address"])
            self._raylet_clients[node_id_hex] = client
        return client

    # ----------------------------------------------------------- node manager
    async def handle_RegisterNode(self, p: dict) -> dict:
        node_id = p["node_id"].hex() if isinstance(p["node_id"], bytes) else p["node_id"]
        self._nodes[node_id] = {
            "node_id": node_id,
            "address": p["address"],
            "object_store_path": p.get("object_store_path", ""),
            "object_store_capacity": p.get("object_store_capacity", 0),
            "resources": p["resources"],
            "state": "ALIVE",
            "last_heartbeat": time.time(),
        }
        await self.publisher.publish("node", {"node_id": node_id, "state": "ALIVE"})
        logger.info("Node %s registered at %s", node_id[:8], p["address"])
        # New capacity invalidates INFEASIBLE verdicts: re-run scheduling
        # for groups that timed out waiting (the autoscaler may have
        # launched this node precisely for them).
        for record in self._placement_groups.values():
            if record["state"] == "INFEASIBLE":
                record["state"] = "PENDING"
                self._spawn(self._schedule_pg_loop(record))
        return {"node_id": node_id}

    async def handle_Heartbeat(self, p: dict) -> dict:
        node = self._nodes.get(p["node_id"])
        if node is None:
            return {"unknown": True}
        node["last_heartbeat"] = time.time()
        if p.get("draining") and not node.get("draining"):
            # Heartbeat-carried drain flag: belt-and-braces sync in case
            # the explicit ReportNodeDraining RPC was lost.
            await self._note_node_draining(
                p["node_id"], p.get("drain_reason", "raylet heartbeat"),
                notice_clock=p.get("drain_notice_clock"))
        if "resources" in p and p["resources"]:
            node["resources"] = p["resources"]
        node["pending_demand"] = p.get("pending_demand", [])
        if "store" in p:
            node["store"] = p["store"]
            # Feed the leak watcher's per-node pinned-bytes trend history.
            self.memory_store.report_node(
                p["node_id"], p["store"].get("pinned_bytes", 0))
        if "hbm" in p:
            node["hbm"] = p["hbm"]
        if "worker_rss_bytes" in p:
            node["worker_rss_bytes"] = p["worker_rss_bytes"]
        # Bundle reconciliation (reference: GCS-restart bundle cleanup):
        # the raylet cancels reservations whose group no longer exists —
        # half-committed 2PC bundles from before a GCS crash would
        # otherwise pin their resources forever.
        return {"live_pgs": list(self._placement_groups.keys())}

    async def handle_GetAllNodes(self, p: dict) -> dict:
        return {"nodes": list(self._nodes.values())}

    async def handle_PublishLogs(self, p: dict) -> dict:
        """Raylet log monitors forward worker output here; drivers long-
        poll it via PollLogs (reference: log pubsub through the GCS)."""
        await self.publisher.publish(
            "logs", {"node_id": p["node_id"], "batch": p["batch"]}
        )
        return {}

    async def handle_PollLogs(self, p: dict) -> dict:
        cursor = p.get("cursor")
        if cursor is None:
            # Baseline request: a newly connected driver starts at the
            # CURRENT end so it doesn't replay other drivers' history.
            return {"cursor": self.publisher.current_seq("logs"), "messages": []}
        out = await self.publisher.poll({"logs": cursor}, p.get("timeout", 10.0))
        msgs = out.get("logs", [])
        return {
            "cursor": msgs[-1][0] if msgs else cursor,
            "messages": [m for _, m in msgs],
        }

    async def handle_DrainNode(self, p: dict) -> dict:
        await self._mark_node_dead(p["node_id"], "drained")
        return {}

    # ------------------------------------------------------------- preemption
    async def handle_ReportNodeDraining(self, p: dict) -> dict:
        """A raylet received a preemption notice and entered draining.
        The node stays ALIVE (it still serves objects and in-flight work)
        but is flagged ``draining`` — schedulers, the autoscaler, and the
        serve controller all treat it as capacity that is about to
        vanish — and a ``node_preempted`` ErrorEvent goes out so
        consumers react to the NOTICE, not the eventual death."""
        if p["node_id"] not in self._nodes:
            return {"unknown": True}
        await self._note_node_draining(
            p["node_id"], p.get("reason", ""),
            notice_clock=p.get("notice_clock"), grace_s=p.get("grace_s"))
        return {}

    async def _note_node_draining(self, node_id: str, reason: str,
                                  notice_clock=None, grace_s=None) -> None:
        node = self._nodes.get(node_id)
        if node is None or node.get("draining") or node["state"] != "ALIVE":
            return
        node["draining"] = True
        node["drain_reason"] = reason
        node["drain_notice_clock"] = (
            float(notice_clock) if notice_clock else chaos_clock.now())
        logger.warning("node %s draining (%s)", node_id[:8], reason)
        from ..diagnostics.errors import make_event

        await self.handle_PublishError({"event": make_event(
            "node_preempted",
            f"node {node_id[:8]} received a preemption notice ({reason}); "
            "draining",
            source="gcs", node_id=node_id,
            extra={"reason": reason, "grace_s": grace_s,
                   "notice_clock": node["drain_notice_clock"]})})

    async def handle_NodePreempted(self, p: dict) -> dict:
        """The drain grace expired: the node is gone (the cloud reclaimed
        the VM). Terminal — actors there restart elsewhere."""
        await self._mark_node_dead(
            p["node_id"], f"preempted ({p.get('reason', '')})")
        return {}

    async def _health_check_loop(self) -> None:
        cfg = get_config()
        period = cfg.health_check_period_ms / 1000.0
        failures: dict[str, int] = {}
        while True:
            await chaos_clock.sleep(period)
            for node_id, node in list(self._nodes.items()):
                if node["state"] != "ALIVE":
                    continue
                client = self._raylet(node_id)
                try:
                    await client.call("HealthCheck", {}, timeout=period * 2)
                    failures[node_id] = 0
                except Exception:
                    failures[node_id] = failures.get(node_id, 0) + 1
                    if failures[node_id] >= cfg.health_check_failure_threshold:
                        await self._mark_node_dead(node_id, "health check failed")

    async def _mark_node_dead(self, node_id: str, reason: str) -> None:
        node = self._nodes.get(node_id)
        if node is None or node["state"] == "DEAD":
            return
        node["state"] = "DEAD"
        logger.warning("Node %s marked DEAD (%s)", node_id[:8], reason)
        await self.publisher.publish("node", {"node_id": node_id, "state": "DEAD"})
        self._raylet_clients.pop(node_id, None)
        # Restart / fail actors that lived there (gcs_actor_manager.cc
        # OnNodeDead).
        for actor in list(self._actors.values()):
            if actor.get("node_id") == node_id and actor["state"] in (ALIVE, PENDING_CREATION):
                await self._restart_or_kill_actor(actor, f"node {node_id[:8]} died")

    # ---------------------------------------------------------- job manager
    async def handle_AddJob(self, p: dict) -> dict:
        job_id = self._next_job
        self._next_job += 1
        self._jobs[str(job_id)] = {
            "job_id": job_id,
            "driver_address": p.get("driver_address", ""),
            "start_time": time.time(),
            "state": "RUNNING",
        }
        return {"job_id": job_id}

    async def handle_FinishJob(self, p: dict) -> dict:
        job = self._jobs.get(str(p["job_id"]))
        if job:
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
        return {}

    async def handle_GetAllJobs(self, p: dict) -> dict:
        return {"jobs": list(self._jobs.values())}

    # ------------------------------------------------------------ internal KV
    async def handle_KvPut(self, p: dict) -> dict:
        key = p["key"]
        overwrite = p.get("overwrite", True)
        exists = key in self._kv
        if exists and not overwrite:
            return {"added": False}
        self._kv[key] = p["value"]
        return {"added": not exists}

    async def handle_KvGet(self, p: dict) -> dict:
        value = self._kv.get(p["key"])
        return {"value": value, "found": value is not None}

    async def handle_KvDel(self, p: dict) -> dict:
        existed = self._kv.pop(p["key"], None) is not None
        return {"deleted": existed}

    async def handle_KvKeys(self, p: dict) -> dict:
        return {"keys": self._kv.keys_with_prefix(p.get("prefix", ""))}

    # --------------------------------------------------------- observability
    async def handle_AddTaskEvents(self, p: dict) -> dict:
        from .task_events import MEMORY, SPAN

        # ONE routing pass per batch (a 100k-task bench flushes tens of
        # thousands of events per interval — the old triple list scan was
        # measurable GIL time), then one locked store ingestion; coalesced
        # events (status-transition bundles) expand inside the store.
        task_events: list[dict] = []
        spans: list[dict] = []
        for e in p.get("events") or []:
            status = e.get("status")
            if status == MEMORY:
                summary = e.get("memory")
                if summary:
                    self.memory_store.report(summary)
            elif status == SPAN:
                # Stamp recorder identity onto the span at ingest so the
                # chrome trace can group tracks per recording worker.
                s = dict(e.get("span") or {})
                s.setdefault("worker_id", e.get("worker_id", ""))
                s.setdefault("node_id", e.get("node_id", ""))
                spans.append(s)
            else:
                task_events.append(e)
        if spans:
            self.span_store.add(spans)
        dropped = p.get("dropped", 0)
        if task_events or dropped:
            # Ingest OFF the event loop: a 100k-task bench flushes tens
            # of thousands of events per interval, and chewing them
            # inline blocked every other RPC (heartbeats, leases) for the
            # duration. The store is sharded with per-shard locks, so
            # flush batches from N raylets ingest concurrently in
            # executor threads.
            await asyncio.get_running_loop().run_in_executor(
                None, self.task_events.add_events, task_events, dropped)
        return {}

    async def handle_ListTaskEvents(self, p: dict) -> dict:
        return {"tasks": self.task_events.list_tasks(p.get("limit", 1000))}

    async def handle_MemorySummary(self, p: dict) -> dict:
        """Merged per-worker memory summaries (state.memory_summary /
        cli memory / dashboard /api/memory)."""
        return {"summary": self.memory_store.summary()}

    async def handle_RegisterProfile(self, p: dict) -> dict:
        """A raylet registers a finished jax.profiler capture artifact."""
        entry = dict(p.get("profile") or {})
        entry.setdefault("ts", time.time())
        self._profiles.append(entry)
        del self._profiles[: max(0, len(self._profiles) - 100)]
        return {}

    async def handle_ListProfiles(self, p: dict) -> dict:
        return {"profiles": list(self._profiles)}

    async def _memory_watch_loop(self) -> None:
        """Leak watcher: scan the memory store's trend histories and turn
        monotonic growth (a worker's refcount table, a raylet's pinned
        bytes) into a diagnostics ErrorEvent naming the top holders by
        callsite (ROADMAP 1c). Re-reads the config each tick so tests and
        live operators can retune thresholds without a restart."""
        from ..observability.memory import leak_event_message
        from ..diagnostics.errors import make_event

        while True:
            cfg = get_config()
            await chaos_clock.sleep(max(0.1, cfg.memory_leak_check_interval_s))
            if cfg.memory_leak_intervals <= 0:
                continue
            try:
                suspects = self.memory_store.detect_leaks(
                    intervals=cfg.memory_leak_intervals,
                    min_growth_bytes=cfg.memory_leak_min_growth_bytes,
                    min_growth_refs=cfg.memory_leak_min_growth_refs)
                for s in suspects:
                    logger.warning("memory leak watcher: %s", leak_event_message(s))
                    await self.handle_PublishError({"event": make_event(
                        "memory_leak", leak_event_message(s), source="gcs",
                        node_id=s.get("node_id", ""),
                        worker_id=s.get("worker_id", ""),
                        extra={"suspect": s})})
            except Exception:
                logger.exception("memory leak watcher scan failed")

    async def handle_ListSpans(self, p: dict) -> dict:
        return {"spans": self.span_store.list_spans(
            p.get("trace_id"), p.get("limit", 1000))}

    async def handle_ListTraces(self, p: dict) -> dict:
        return {"traces": self.span_store.list_traces(p.get("limit", 100))}

    async def handle_Timeline(self, p: dict) -> dict:
        # Task slices + trace spans in one chrome trace: spans appear as
        # nested per-trace flows alongside the per-node task tracks.
        return {"trace": self.task_events.chrome_trace()
                + self.span_store.chrome_trace()}

    # ----------------------------------------------------------- error info
    async def handle_PublishError(self, p: dict) -> dict:
        """Record + broadcast an ErrorEvent (reference
        ``publish_error_to_driver`` → RAY_ERROR_INFO_CHANNEL). The event is
        retained in a bounded table for ``ListErrors`` AND published on the
        long-poll channel for live driver subscribers."""
        from ..diagnostics.errors import ERROR_INFO_CHANNEL

        event = dict(p.get("event") or {})
        event.setdefault("timestamp", time.time())
        self._errors.append(event)
        max_events = get_config().error_info_buffer_size
        if len(self._errors) > max_events:
            del self._errors[: len(self._errors) - max_events]
        await self.publisher.publish(ERROR_INFO_CHANNEL, event)
        return {}

    async def handle_ListErrors(self, p: dict) -> dict:
        """Filtered view of retained ErrorEvents. ``limit=0`` returns no
        events — used by drivers to fetch just the channel cursor before
        subscribing (no history replay)."""
        from ..diagnostics.errors import ERROR_INFO_CHANNEL

        source, etype = p.get("source"), p.get("type")
        limit = p.get("limit", 100)
        out = [
            e for e in self._errors
            if (not source or e.get("source") == source)
            and (not etype or e.get("type") == etype)
        ]
        return {
            "errors": out[-limit:] if limit else [],
            "cursor": self.publisher.current_seq(ERROR_INFO_CHANNEL),
        }

    def _debug_state_snapshot(self) -> dict:
        """Control-plane FSM counts (the GCS half of debug_state.txt)."""
        def by_state(records, key: str = "state") -> dict[str, int]:
            out: dict[str, int] = {}
            for r in records:
                s = r.get(key, "?")
                out[s] = out.get(s, 0) + 1
            return out

        return {
            "num_nodes": len(self._nodes),
            "nodes_by_state": by_state(self._nodes.values()),
            "actors_by_state": by_state(self._actors.values()),
            "named_actors": len(self._named_actors),
            "placement_groups_by_state": by_state(self._placement_groups.values()),
            "jobs_by_state": by_state(self._jobs.values()),
            "kv_keys": len(self._kv),
            "tasks_by_state": self.task_events.count_by_state(),
            "errors_buffered": len(self._errors),
            "spans_buffered": self.span_store.size(),
            "memory_reports": self.memory_store.size(),
            "memory_leaks_flagged_total": self.memory_store.leaks_flagged_total,
            "profiles_registered": len(self._profiles),
            "pubsub_publishes_total": self.publisher.publishes_total,
            "pubsub_notify_batches_total": self.publisher.notify_batches_total,
        }

    async def handle_GetDebugState(self, p: dict) -> dict:
        return {"debug_state": self._debug_state_snapshot()}

    async def _debug_dump_loop(self) -> None:
        """Periodic ``debug_state_gcs.txt`` in the session dir (reference:
        every component dumps its DebugString on an interval)."""
        import os

        from ..diagnostics.debug_state import write_debug_state

        last = 0.0
        while True:
            await asyncio.sleep(0.5)
            interval = get_config().debug_state_dump_interval_s
            now = time.monotonic()
            if interval <= 0 or now - last < interval:
                continue
            last = now
            try:
                path = os.path.join(self._session_dir, "debug_state_gcs.txt")
                snapshot = self._debug_state_snapshot()
                await asyncio.get_running_loop().run_in_executor(
                    None, write_debug_state, path, "GCS", snapshot)
            except Exception:
                logger.exception("GCS debug-state dump failed")

    async def handle_ListPlacementGroups(self, p: dict) -> dict:
        return {
            "placement_groups": [
                {"pg_id": r["pg_id"], "state": r["state"], "strategy": r["strategy"],
                 "bundles": r["bundles"], "name": r.get("name", "")}
                for r in self._placement_groups.values()
            ]
        }

    async def handle_ReportMetrics(self, p: dict) -> dict:
        self._metrics[p["worker_id"]] = (time.time(), p.get("metrics") or [])
        return {}

    async def handle_GetMetrics(self, p: dict) -> dict:
        """Aggregate across workers: counters/histogram sums add, gauges
        add (per-worker gauges are usually disjoint by tags). Snapshots
        from workers silent for >30s (dead) are dropped."""
        now = time.time()
        merged: dict[tuple, dict] = {}
        for worker_id, (ts, snapshot) in list(self._metrics.items()):
            if now - ts > 30.0:
                del self._metrics[worker_id]
                continue
            for m in snapshot:
                key = (m["name"], tuple(sorted((m.get("tags") or {}).items())))
                cur = merged.get(key)
                if cur is None:
                    merged[key] = dict(m)
                elif m.get("type") == "histogram":
                    cur["value"] = cur.get("value", 0.0) + m.get("value", 0.0)
                    cur["count"] = cur.get("count", 0) + m.get("count", 0)
                    if cur.get("boundaries") == m.get("boundaries"):
                        cur["buckets"] = [
                            a + b for a, b in zip(cur.get("buckets", []), m.get("buckets", []))
                        ]
                    else:  # incompatible shapes: bucket detail unavailable
                        cur.pop("buckets", None)
                else:
                    cur["value"] = cur.get("value", 0.0) + m.get("value", 0.0)
        return {"metrics": list(merged.values()) + self._framework_metrics()}

    def _framework_metrics(self) -> list[dict]:
        """Cluster-state gauges (``ray_tpu_*``) synthesized from GCS tables
        on every scrape — nodes/actors/tasks/PGs by state, per-resource
        totals and usage, pending demand. These back the generated Grafana
        dashboard (``ray_tpu/grafana.py``; reference
        ``dashboard/modules/metrics/grafana_dashboard_factory.py``)."""
        out: list[dict] = []

        def gauge(name: str, value: float, **tags) -> None:
            out.append({"name": name, "type": "gauge", "value": value, "tags": tags})

        by_state: dict[str, int] = {}
        for n in self._nodes.values():
            by_state[n.get("state", "?")] = by_state.get(n.get("state", "?"), 0) + 1
        for state, count in by_state.items():
            gauge("ray_tpu_nodes", count, state=state)

        totals: dict[str, float] = {}
        avail: dict[str, float] = {}
        demand: dict[str, int] = {}
        for n in self._nodes.values():
            if n.get("state") != "ALIVE":
                continue
            res = n.get("resources") or {}
            for k, v in (res.get("total") or {}).items():
                totals[k] = totals.get(k, 0.0) + float(v)
            for k, v in (res.get("available") or {}).items():
                avail[k] = avail.get(k, 0.0) + float(v)
            for d in n.get("pending_demand") or []:
                shape = ",".join(
                    f"{k}:{v:g}" for k, v in sorted((d.get("shape") or {}).items()))
                demand[shape] = demand.get(shape, 0) + d.get("count", 0)
        for k, v in totals.items():
            gauge("ray_tpu_resource_total", v, resource=k)
            gauge("ray_tpu_resource_used", v - avail.get(k, 0.0), resource=k)
        if not demand:
            demand[""] = 0  # always expose the series, even when idle
        for shape, count in demand.items():
            gauge("ray_tpu_pending_demand", count, shape=shape)

        worker_hbm = self.memory_store.hbm_by_node()
        for node_id, n in self._nodes.items():
            if n.get("state") != "ALIVE":
                continue
            nid = node_id[:12]
            store = n.get("store") or {}
            gauge("ray_tpu_object_store_used_bytes", store.get("used", 0), node_id=nid)
            gauge("ray_tpu_object_store_capacity_bytes",
                  store.get("capacity", n.get("object_store_capacity", 0)), node_id=nid)
            gauge("ray_tpu_object_store_pinned_bytes", store.get("pinned_bytes", 0), node_id=nid)
            gauge("ray_tpu_object_store_used_peak_bytes",
                  store.get("used_peak", store.get("used", 0)), node_id=nid)
            gauge("ray_tpu_object_store_fallback_allocations_total",
                  store.get("fallback_allocations_total", 0), node_id=nid)
            # Spill/restore counters: bytes AND object counts (canonical
            # ray_tpu_spill_* names; the legacy *_bytes_total spellings from
            # the first metrics PR stay for existing dashboards).
            gauge("ray_tpu_spill_bytes_total", store.get("spilled_bytes_total", 0), node_id=nid)
            gauge("ray_tpu_restore_bytes_total", store.get("restored_bytes_total", 0), node_id=nid)
            gauge("ray_tpu_spill_objects_total", store.get("spilled_objects_total", 0), node_id=nid)
            gauge("ray_tpu_restore_objects_total", store.get("restored_objects_total", 0), node_id=nid)
            gauge("ray_tpu_spilled_bytes_total", store.get("spilled_bytes_total", 0), node_id=nid)
            gauge("ray_tpu_restored_bytes_total", store.get("restored_bytes_total", 0), node_id=nid)
            # HBM accounting: the raylet's own heartbeat view merged (max)
            # with what the node's workers report in memory summaries — the
            # device lock is exclusive per process, and max never double
            # counts a driver sharing the raylet's process.
            hbm = dict(n.get("hbm") or {})
            whbm = worker_hbm.get(node_id) or {}
            for k in ("used", "limit", "peak"):
                hbm[k] = max(int(hbm.get(k, 0)), int(whbm.get(k, 0)))
            gauge("ray_tpu_hbm_used_bytes", hbm.get("used", 0), node_id=nid)
            gauge("ray_tpu_hbm_limit_bytes", hbm.get("limit", 0), node_id=nid)
            gauge("ray_tpu_hbm_peak_bytes", hbm.get("peak", 0), node_id=nid)
            gauge("ray_tpu_worker_rss_bytes", n.get("worker_rss_bytes", 0), node_id=nid)

        by_state = {}
        for a in self._actors.values():
            by_state[a.get("state", "?")] = by_state.get(a.get("state", "?"), 0) + 1
        for state, count in by_state.items():
            gauge("ray_tpu_actors", count, state=state)

        for state, count in self.task_events.count_by_state().items():
            gauge("ray_tpu_tasks", count, state=state)

        by_state = {}
        for r in self._placement_groups.values():
            by_state[r.get("state", "?")] = by_state.get(r.get("state", "?"), 0) + 1
        for state, count in by_state.items():
            gauge("ray_tpu_placement_groups", count, state=state)
        out.extend(self._lease_stage_hist.snapshot())
        return out

    # --------------------------------------------------------------- pub/sub
    async def handle_Publish(self, p: dict) -> dict:
        await self.publisher.publish(p["channel"], p["message"])
        return {}

    async def handle_SubscribePoll(self, p: dict) -> dict:
        cfg = get_config()
        timeout = min(p.get("timeout", cfg.gcs_pubsub_poll_timeout_s), cfg.gcs_pubsub_poll_timeout_s)
        out = await self.publisher.poll(p["cursors"], timeout)
        return {"messages": out}

    # ---------------------------------------------------------- actor manager
    async def handle_RegisterActor(self, p: dict) -> dict:
        """Register + asynchronously create an actor (gcs_actor_manager.cc:389,475)."""
        spec = p["spec"]
        actor_id = spec["actor_id"].hex() if isinstance(spec["actor_id"], bytes) else spec["actor_id"]
        name = p.get("name", "")
        if name:
            if name in self._named_actors:
                return {"error": f"Actor name '{name}' already taken"}
            self._named_actors[name] = actor_id
        record = {
            "actor_id": actor_id,
            "name": name,
            "spec": spec,
            "state": PENDING_CREATION,
            "address": "",
            "node_id": "",
            "worker_id": "",
            "num_restarts": 0,
            "max_restarts": spec.get("max_restarts", 0),
            "detached": p.get("detached", False),
            "death_cause": "",
        }
        self._actors[actor_id] = record
        self._spawn(self._create_actor(record))
        return {"actor_id": actor_id}

    async def _create_actor(self, record: dict) -> None:
        """Lease a worker and push the creation task (GcsActorScheduler).

        Invariant: a granted dedicated lease is ALWAYS either promoted to a
        live actor or returned to its raylet (killing the worker) — failed
        creations must not strand leased resources."""
        spec = record["spec"]
        resources = spec.get("resources") or {"CPU": 1.0}
        strategy = spec.get("scheduling_strategy") or {}

        def _stamp_creation(status: str, worker_id: str = "",
                            node_id: str = "") -> None:
            # Submitter-side terminal status for the creation task: the
            # executor records one too, but its buffer dies unflushed if
            # the worker is killed right after (or during) creation —
            # every settled creation must look settled in list_tasks().
            self.task_events.add_events([{
                "task_id": spec["task_id"], "status": status,
                "ts": time.time(), "name": spec.get("name", ""),
                "kind": spec.get("kind", 1),
                "worker_id": worker_id, "node_id": node_id,
            }])

        for attempt in range(60):
            if record["state"] == DEAD:  # killed while pending
                _stamp_creation("FAILED")
                return
            pg_id = spec.get("placement_group_id") or b""
            if pg_id:
                # PG-bundled actor: the bundle RESERVED its resources, so
                # availability-based selection would see a full cluster and
                # never place it — go straight to the bundle's node (the
                # raylet grants the lease from the bundle reservation).
                pg_hex = pg_id.hex() if isinstance(pg_id, bytes) else pg_id
                pg_rec = self._placement_groups.get(pg_hex)
                locs = (pg_rec or {}).get("bundle_locations") or []
                idx = spec.get("placement_group_bundle_index", -1)
                node_id = (locs[idx] if 0 <= idx < len(locs)
                           else (locs[0] if locs else None))
            else:
                node_id = self._select_node(resources, strategy)
            if node_id is None:
                await asyncio.sleep(0.5)
                continue
            client = self._raylet(node_id)
            if client is None:
                continue
            try:
                lease = await client.call(
                    "RequestWorkerLease",
                    {"spec": spec, "dedicated": True},
                    timeout=get_config().worker_register_timeout_s + 10.0,
                )
            except Exception as e:
                logger.warning("Actor lease on node %s failed: %s", node_id[:8], e)
                await asyncio.sleep(0.2)
                continue
            if lease.get("spillback"):
                continue  # re-select with fresh view
            if not lease.get("granted"):
                # Only a resource WAIT suggests capacity pinned by garbage
                # (un-collected actor-handle cycles) — infeasible requests
                # and worker-start failures would just churn gc.collect()
                # cluster-wide for nothing.
                if "waiting for resources" in lease.get("reason", ""):
                    await self._maybe_global_gc("actor_pending")
                await asyncio.sleep(0.2)
                continue
            worker_addr = lease["worker_address"]
            worker_id = lease.get("worker_id", "")
            try:
                # Confirm the grant reply arrived (AckLease): un-acked
                # leases are reclaimed by the raylet's orphan watchdog.
                await client.call("AckLease", {"worker_id": worker_id},
                                  timeout=10.0)
            except Exception:
                pass

            async def _return_lease(kill: bool) -> None:
                try:
                    await client.call("ReturnWorker", {"worker_id": worker_id, "kill": kill}, timeout=10.0)
                except Exception as e:
                    logger.warning(
                        "actor %s: returning dedicated lease %s failed (%s)",
                        record["actor_id"][:8], worker_id[:8], e)

            logger.info("Actor %s: pushing creation task to %s", record["actor_id"][:8], worker_addr)
            try:
                worker = RpcClient(worker_addr)
                reply = await worker.call(
                    "PushTask", {"spec": spec}, timeout=get_config().worker_register_timeout_s * 2
                )
                await worker.close()
                logger.info("Actor %s: creation reply %s", record["actor_id"][:8], "err" if reply.get("error") else "ok")
                _stamp_creation("FAILED" if reply.get("error") else "FINISHED",
                                worker_id, node_id)
                if reply.get("error"):
                    await _return_lease(kill=True)
                    record["state"] = DEAD
                    record["death_cause"] = f"creation task failed: {reply['error']}"
                    if record.get("name"):
                        self._named_actors.pop(record["name"], None)
                    await self._publish_actor(record)
                    return
            except Exception as e:
                record["death_cause"] = f"creation push failed: {e}"
                await _return_lease(kill=True)
                await asyncio.sleep(0.2)
                continue
            if record["state"] == DEAD:  # ray.kill raced with creation
                await _return_lease(kill=True)
                return  # (terminal status already stamped above)
            record["state"] = ALIVE
            record["address"] = worker_addr
            record["node_id"] = node_id
            record["worker_id"] = worker_id
            await self._publish_actor(record)
            return
        record["state"] = DEAD
        record["death_cause"] = record.get("death_cause") or "no node could schedule the actor"
        _stamp_creation("FAILED")
        await self._publish_actor(record)

    def _select_node(self, resources: dict, strategy: dict | None = None) -> str | None:
        from .scheduling import select_node_for_resources

        node_id = select_node_for_resources(self._nodes, resources,
                                            strategy or {})
        if node_id is not None:
            # Optimistic bookkeeping (reference GcsActorScheduler): deduct
            # the selection from the cached availability view NOW, so a
            # 1k-actor creation storm spreads across raylets instead of
            # every coroutine picking the same node off the same stale
            # heartbeat snapshot and convoying in one admission queue.
            # The next heartbeat overwrites the view with ground truth.
            avail = (self._nodes[node_id].get("resources") or {}).get(
                "available") or {}
            for k, v in (resources or {}).items():
                if k in avail:
                    avail[k] = avail[k] - float(v)
        return node_id

    async def _publish_actor(self, record: dict) -> None:
        await self.publisher.publish(
            "actor",
            {
                "actor_id": record["actor_id"],
                "state": record["state"],
                "address": record["address"],
                "num_restarts": record["num_restarts"],
                "death_cause": record["death_cause"],
            },
        )

    async def handle_GetActorInfo(self, p: dict) -> dict:
        actor_id = p["actor_id"]
        record = self._actors.get(actor_id)
        if record is None:
            return {"found": False}
        return {
            "found": True,
            "state": record["state"],
            "address": record["address"],
            "node_id": record.get("node_id", ""),
            "num_restarts": record["num_restarts"],
            "death_cause": record["death_cause"],
        }

    async def handle_GetActorByName(self, p: dict) -> dict:
        actor_id = self._named_actors.get(p["name"])
        if actor_id is None:
            return {"found": False}
        info = await self.handle_GetActorInfo({"actor_id": actor_id})
        info["actor_id"] = actor_id
        info["spec"] = self._actors[actor_id]["spec"]
        return info

    async def handle_ListActors(self, p: dict) -> dict:
        return {
            "actors": [
                {k: v for k, v in rec.items() if k != "spec"}
                for rec in self._actors.values()
            ]
        }

    async def handle_ReportActorDeath(self, p: dict) -> dict:
        """Raylet/worker reports an actor's process died (OnWorkerDead)."""
        record = self._actors.get(p["actor_id"])
        if record is None or record["state"] == DEAD:
            return {}
        if record["state"] in (RESTARTING, PENDING_CREATION):
            # A restart/creation is already in flight for this actor —
            # this report describes the SAME death that triggered it (the
            # preempted node's drain kill races its own worker-monitor
            # report). Spawning a second _create_actor here double-created
            # the actor: two dedicated leases, one leaked forever.
            # Failures of the in-flight creation surface through its own
            # push path, never through this report.
            return {}
        if p.get("worker_id") and record.get("worker_id") \
                and p["worker_id"] != record["worker_id"]:
            # Stale report about a PREVIOUS incarnation's worker arriving
            # after the restarted actor went ALIVE: must not kill the
            # live incarnation.
            return {}
        await self._restart_or_kill_actor(record, p.get("reason", "worker died"))
        return {}

    async def handle_KillActor(self, p: dict) -> dict:
        record = self._actors.get(p["actor_id"])
        if record is None:
            return {"found": False}
        record["max_restarts"] = 0  # no_restart
        node = self._raylet(record["node_id"]) if record["node_id"] else None
        if record["state"] == ALIVE and record["address"]:
            try:
                w = RpcClient(record["address"])
                await w.call("Exit", {}, timeout=2.0)
                await w.close()
            except Exception:
                pass
        if node is not None and record.get("worker_id"):
            # Belt and braces through the RAYLET: the Exit RPC above is
            # best-effort against the worker's own loop — under a storm
            # it can time out and the dedicated worker (plus its CPU
            # lease) leaked forever. ReturnWorker(kill) is idempotent if
            # the Exit already landed.
            try:
                await node.call(
                    "ReturnWorker",
                    {"worker_id": record["worker_id"], "kill": True},
                    timeout=5.0)
            except Exception:
                pass
        record["state"] = DEAD
        record["death_cause"] = "killed via ray.kill"
        if record.get("name"):
            self._named_actors.pop(record["name"], None)
        await self._publish_actor(record)
        return {"found": True}

    async def _restart_or_kill_actor(self, record: dict, reason: str) -> None:
        """The restart FSM (gcs_actor_manager.cc:565 RestartActor)."""
        max_restarts = record.get("max_restarts", 0)
        if max_restarts == -1 or record["num_restarts"] < max_restarts:
            record["num_restarts"] += 1
            record["state"] = RESTARTING
            record["address"] = ""
            await self._publish_actor(record)
            self._spawn(self._create_actor(record))
        else:
            record["state"] = DEAD
            record["death_cause"] = reason
            if record.get("name"):
                self._named_actors.pop(record["name"], None)
            await self._publish_actor(record)

    # ------------------------------------------------------ placement groups
    async def handle_CreatePlacementGroup(self, p: dict) -> dict:
        pg_id = p["pg_id"].hex() if isinstance(p["pg_id"], bytes) else p["pg_id"]
        record = {
            "pg_id": pg_id,
            "bundles": p["bundles"],
            "strategy": p.get("strategy", "PACK"),
            "state": "PENDING",
            "bundle_locations": [],
            "name": p.get("name", ""),
        }
        self._placement_groups[pg_id] = record
        self._spawn(self._schedule_pg_loop(record))
        return {"pg_id": pg_id, "state": record["state"]}

    async def _schedule_pg_loop(self, record: dict) -> None:
        """Keep a PENDING group scheduling until it is placed or removed.

        A group whose bundles exceed every node's TOTAL resources is
        terminally INFEASIBLE; one that merely doesn't fit the currently
        AVAILABLE resources stays PENDING and is retried as resources free
        up (reference: GcsPlacementGroupManager pending queue,
        ``gcs_placement_group_scheduler.h:117-119`` 2PC)."""
        from .scheduling import schedule_placement_group

        infeasible_since: float | None = None
        while record["state"] == "PENDING":
            if self._nodes:
                feasible = schedule_placement_group(
                    self._nodes, record["bundles"], record["strategy"], use_total=True
                )
                if feasible is None:
                    # Only terminally INFEASIBLE if the totals check keeps
                    # failing for a grace window — nodes may still be
                    # registering (late raylets must not doom the group).
                    now = time.time()
                    if infeasible_since is None:
                        infeasible_since = now
                    elif now - infeasible_since > 10.0:
                        record["state"] = "INFEASIBLE"
                        return
                else:
                    infeasible_since = None
                    placement = schedule_placement_group(
                        self._nodes, record["bundles"], record["strategy"]
                    )
                    if placement is not None and await self._try_reserve(record, placement):
                        return
                    # Feasible on totals but unplaceable on available
                    # resources: capacity may be pinned by garbage (e.g.
                    # actor handles stuck in exception→frame reference
                    # cycles in some driver). Broadcast a global GC so every
                    # worker runs gc.collect() (reference:
                    # ``ray._private.internal_api.global_gc``,
                    # ``core_worker.cc`` TriggerGlobalGC on PG pending).
                    await self._maybe_global_gc("pg_pending")
            await asyncio.sleep(0.25)

    async def _maybe_global_gc(self, reason: str) -> None:
        """Publish a rate-limited global-GC broadcast (at most every 5s)."""
        now = time.time()
        if now - getattr(self, "_last_global_gc", 0.0) < get_config().global_gc_interval_s:
            return
        self._last_global_gc = now
        await self.publisher.publish("global_gc", {"reason": reason})

    async def handle_PollGlobalGc(self, p: dict) -> dict:
        """Worker long-poll for global-GC broadcasts. ``cursor=None`` means
        "start at the current end" (no replay of old triggers)."""
        cursor = p.get("cursor")
        current = self.publisher.current_seq("global_gc")
        if cursor is None or cursor > current:
            # None = "start at the end". A cursor PAST the end means this
            # GCS restarted (fresh Publisher, seqs reset): clamp, or the
            # worker would filter every future broadcast forever.
            return {"cursor": current, "triggered": False}
        out = await self.publisher.poll({"global_gc": cursor}, p.get("timeout", 10.0))
        msgs = out.get("global_gc", [])
        if msgs:
            return {"cursor": msgs[-1][0], "triggered": True}
        return {"cursor": cursor, "triggered": False}

    async def _try_reserve(self, record: dict, placement: list[str]) -> bool:
        """2PC: reserve every bundle, then commit; cancel all on any failure."""
        pg_id = record["pg_id"]
        reserved: list[tuple[int, str]] = []
        ok = True
        for idx, node_id in enumerate(placement):
            client = self._raylet(node_id)
            try:
                r = await client.call(
                    "ReserveBundle",
                    {"pg_id": pg_id, "bundle_index": idx, "resources": record["bundles"][idx]},
                    timeout=5.0,
                )
                if not r.get("ok"):
                    ok = False
                    break
                reserved.append((idx, node_id))
            except Exception:
                ok = False
                break
        # RemovePlacementGroup may have raced with the reservations: roll
        # back instead of committing, or the raylet-side reservations leak.
        if not ok or record["state"] != "PENDING":
            for idx, node_id in reserved:
                client = self._raylet(node_id)
                try:
                    await client.call("CancelBundle", {"pg_id": pg_id, "bundle_index": idx}, timeout=5.0)
                except Exception:
                    pass
            return record["state"] != "PENDING"  # stop the loop if removed
        for idx, node_id in reserved:
            client = self._raylet(node_id)
            await client.call("CommitBundle", {"pg_id": pg_id, "bundle_index": idx}, timeout=5.0)
        record["bundle_locations"] = [n for _, n in sorted(reserved)]
        if record["state"] != "PENDING":
            # removed mid-commit: release everything we just committed
            for idx, node_id in enumerate(record["bundle_locations"]):
                client = self._raylet(node_id)
                try:
                    await client.call("ReturnBundle", {"pg_id": pg_id, "bundle_index": idx}, timeout=5.0)
                except Exception:
                    pass
            return True
        record["state"] = "CREATED"
        return True

    async def handle_GetPlacementGroup(self, p: dict) -> dict:
        record = self._placement_groups.get(p["pg_id"])
        return {"found": record is not None, "pg": record}

    async def handle_RemovePlacementGroup(self, p: dict) -> dict:
        record = self._placement_groups.pop(p["pg_id"], None)
        if record and record["state"] == "PENDING":
            record["state"] = "REMOVED"  # stops the scheduling loop
        if record and record["state"] == "CREATED":
            for idx, node_id in enumerate(record["bundle_locations"]):
                client = self._raylet(node_id)
                if client:
                    try:
                        await client.call("ReturnBundle", {"pg_id": record["pg_id"], "bundle_index": idx}, timeout=5.0)
                    except Exception:
                        pass
        return {"removed": record is not None}
