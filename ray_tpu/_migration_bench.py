"""KV-migration bench: migrated vs cold TTFT + transfer throughput.

ISSUE 11 acceptance cells, runnable standalone (``python -m ray_tpu.cli
bench migration``) or inside ``bench.py``:

  * ``serve_ttft_cold_ms`` — TTFT of a never-seen ~2k-token prompt
    through the real serve stack (proxyless driver handle → router →
    replica → engine): the full cold prefill.
  * ``serve_ttft_migrated_ms`` — TTFT of the SAME prompt after its
    prefix group is forced to spill to the other replica with spill
    migration on: the target pulls the hot KV pages from the previous
    replica and prefills only the suffix. The acceptance bound is
    migrated ≤ 0.7× cold at this 2k cell.
  * ``kv_migration_parity`` — 1.0 iff the migrated request's greedy
    bytes match the cold request's (must be 1.0).
  * ``kv_migration_mb_s`` — raw page-transfer throughput of the
    streaming path (TcpLoopServer wire + device copies), engine-level.

CPU-sandbox friendly (debug preset engines); on chip boxes set
``RAY_TPU_BENCH_SKIP_MIGRATION=1`` to leave ``*_skipped`` markers that
``bench_check`` honors.
"""

from __future__ import annotations

import os
import statistics
import threading
import time

SKIP_MARKERS = {
    "serve_ttft_migrated_skipped": True,
    "kv_migration_mb_s_skipped": True,
}


def _stream_ttft(handle, body: dict, timeout: float = 300.0):
    """Drive one streaming completion through a DeploymentHandle and
    return (ttft_s, text) from the SSE wire messages."""
    import json

    t0 = time.perf_counter()
    stream = handle.remote_streaming(dict(body))
    ttft = None
    text = ""
    try:
        for msg in stream:
            if msg.get("kind") != "chunk":
                continue
            for line in msg.get("data", b"").decode().splitlines():
                line = line.strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                if ttft is None:
                    ttft = time.perf_counter() - t0
                text += json.loads(line[6:])["choices"][0]["text"]
    finally:
        try:
            stream.close()
        except Exception:
            pass
    return ttft, text


def _raw_transfer_mb_s(preset: str, prompt_tokens: int, page_size: int) -> float:
    """Engine-level streaming transfer throughput: prime engine A, then
    stream its pages to engine B over the real TCP loop channel."""
    from ray_tpu.llm.engine import InferenceEngine, Request
    from ray_tpu.llm.migration import KVMigrationSource, receive_kv_stream

    max_len = prompt_tokens + 2 * page_size
    a = InferenceEngine(preset, max_slots=2, max_len=max_len,
                        page_size=page_size, prefill_chunk_size=4 * page_size)
    prompt = [(7 + 13 * i) % 200 + 1 for i in range(prompt_tokens)]
    r = Request("mig-src", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    a.add_request(r)
    while not r.done:
        a.step()
    # The request is already prefilled: the source streams every page at
    # wire speed, so stats measure pure transfer (channel + device
    # copies). Two rounds, best kept — the first pays the gather/scatter
    # program compiles that steady-state migrations never see.
    pages = list(r.export_pinned)
    best = None
    for _ in range(2):
        with a._lock:  # re-pin: each source releases the pins when done
            for pid in pages:
                a.allocator.share(pid)
        r.export_pinned = list(pages)
        src = KVMigrationSource(a, r)
        b = InferenceEngine(preset, max_slots=2, max_len=max_len,
                            page_size=page_size,
                            prefill_chunk_size=4 * page_size)
        stats = receive_kv_stream(b, src.address, timeout_s=120.0)
        src.close()
        if not stats["complete"] or not stats["seconds"]:
            raise RuntimeError(f"raw transfer failed: {stats}")
        rate = stats["bytes"] / 1e6 / stats["seconds"]
        best = rate if best is None else max(best, rate)
    return best


def run_migration_bench(samples: int | None = None) -> dict:
    if os.environ.get("RAY_TPU_BENCH_SKIP_MIGRATION") == "1":
        return dict(SKIP_MARKERS)
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import get_config
    from ray_tpu.llm import build_llm_app

    preset = os.environ.get("RAY_TPU_MIGRATION_PRESET", "debug-128")
    samples = samples or int(os.environ.get("RAY_TPU_MIGRATION_SAMPLES", "3"))
    page_size = 64
    max_tokens = 8
    # ~2k-token prompts under the byte tokenizer (the acceptance cell).
    prefix_len = int(os.environ.get("RAY_TPU_MIGRATION_PROMPT", "2048")) - 64

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    app = build_llm_app(
        preset, num_replicas=2, max_slots=8,
        max_len=prefix_len + 64 + 4 * page_size, page_size=page_size,
        prefill_chunk_size=256, max_ongoing_requests=32)
    serve.run(app, name="llm-mig-bench", timeout_s=360.0)
    out: dict = {}
    try:
        base = serve.get_app_handle("llm-mig-bench")
        cfg = get_config()
        # Warm the compile caches off the measurement.
        warm = base.options(method_name="completions", prefix_group="mig-w")
        _stream_ttft(warm, {"prompt": "w" * 300, "max_tokens": 4,
                            "stream": True})
        cold_ttfts: list[float] = []
        mig_ttfts: list[float] = []
        parity = 1.0
        for i in range(-1, samples):
            # i == -1 is an UNRECORDED warmup pair: it compiles the
            # prefill buckets and the export/import gather/scatter
            # programs on both replicas, so the timed cells measure
            # steady-state migration, not first-touch XLA compiles.
            group = f"mig-bench-{i}"
            h = base.options(method_name="completions", prefix_group=group)
            prompt = (f"[system prompt {i}] "
                      + "You are a terse assistant. Answer carefully. "
                      * (prefix_len // 47) + f" tail {i}: " + "wxyz" * 8)
            body = {"prompt": prompt, "max_tokens": max_tokens,
                    "stream": True}
            t_cold, text_cold = _stream_ttft(h, body)
            if t_cold is not None and i >= 0:
                cold_ttfts.append(t_cold)
            # Force the group to spill to the OTHER replica: run the
            # affine replica's in-flight count past the spill margin, so
            # the router ships a migrate-from source with the request.
            router = h._get_router()
            affine = router._group_affinity.get(group)
            bump = cfg.serve_affinity_spill_margin + 1
            if affine is not None:
                with router._cond:
                    router._inflight[affine] = \
                        router._inflight.get(affine, 0) + bump
            try:
                t_mig, text_mig = _stream_ttft(h, body)
            finally:
                if affine is not None:
                    with router._cond:
                        router._inflight[affine] = max(
                            0, router._inflight.get(affine, 0) - bump)
            if i < 0:
                continue
            if t_mig is not None:
                mig_ttfts.append(t_mig)
            if text_mig != text_cold:
                parity = 0.0
        spill_migrations = router.spill_migrations
        if cold_ttfts and mig_ttfts:
            out["serve_ttft_cold_ms"] = round(
                1000 * statistics.median(cold_ttfts), 1)
            out["serve_ttft_migrated_ms"] = round(
                1000 * statistics.median(mig_ttfts), 1)
            out["kv_migration_parity"] = parity
            out["serve_spill_migrations"] = spill_migrations
        else:
            out.update(SKIP_MARKERS)
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
    try:
        out["kv_migration_mb_s"] = round(
            _raw_transfer_mb_s(preset, 2048, page_size), 1)
    except Exception as e:
        out["kv_migration_mb_s_skipped"] = True
        out["kv_migration_error"] = f"{type(e).__name__}: {e}"
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    return out


if __name__ == "__main__":
    import json

    print(json.dumps(run_migration_bench()))
