"""Collective ops for use inside ``shard_map`` program bodies.

API-compatible surface with the reference's ``ray.util.collective``
(``collective.py:268-625`` — allreduce/allgather/reducescatter/broadcast/
send/recv) but compiled into the XLA program over ICI rather than issued
to NCCL at runtime. Each function takes the mesh axis name instead of a
process group.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis: str = "dp", op: str = "sum"):
    """Reference: collective.py:268 (allreduce)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x, axis: str, *, tiled: bool = True, gather_dim: int = 0):
    """Reference: collective.py:433 (allgather)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dim: int = 0):
    """Reference: collective.py:482 (reducescatter)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def broadcast(x, axis: str, root: int = 0):
    """Reference: collective.py:383 — root's shard replicated to all."""
    full = lax.all_gather(x, axis, axis=0, tiled=False)
    return full[root]


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int):
    """Ulysses-style sequence<->head reshuffle primitive."""
    return lax.all_to_all(
        x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True
    )


def ppermute(x, axis: str, *, shift: int = 1):
    """Ring shift: device i sends to (i+shift) mod n. The building block of
    ring attention (SURVEY.md §5.7)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def barrier(axis: str):
    """Synchronize all devices on an axis (psum of a unit scalar)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


def send_recv(x, axis: str, pairs: list[tuple[int, int]]):
    """Point-to-point via ppermute perm list. Reference: collective.py:541/604
    (send/recv) — in XLA both sides are one collective permute."""
    return lax.ppermute(x, axis, perm=pairs)
