"""Multi-process (multi-host) JAX bootstrap helpers.

The SPMD↔actor bridge (SURVEY.md §7.1): a controller creates one actor
per host, rank 0 picks a coordinator endpoint, and every process calls
``jax.distributed.initialize`` — the analogue of the reference's
``_setup_torch_process_group`` (``python/ray/train/torch/config.py:66``).
Shared by Train worker groups and multi-host LLM engine shards.
"""

from __future__ import annotations


def pick_coordinator_address() -> str:
    """Pick a routable ``host:port`` for the jax.distributed coordinator
    (rank 0 binds and serves it). A UDP "connect" selects the outbound
    interface without sending traffic — ``gethostbyname(gethostname())``
    resolves to loopback on common /etc/hosts setups, which would break
    every cross-host join."""
    import socket

    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("8.8.8.8", 80))
        host = probe.getsockname()[0]
        probe.close()
    except OSError:
        host = socket.gethostbyname(socket.gethostname())
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return f"{host}:{port}"


def initialize_process(coordinator: str, num_processes: int, process_id: int) -> int:
    """``jax.distributed.initialize`` for one process of a multi-host
    group; returns the GLOBAL device count. On the CPU backend (tests,
    dryruns) cross-process collectives need the gloo implementation —
    configure it before the backend initializes."""
    import jax

    if num_processes > 1:
        try:
            import os

            if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or (
                    jax.config.jax_platforms or "").startswith("cpu"):
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jaxlib without gloo: TPU/real backends don't need it
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return len(jax.devices())
