"""Logical-axis sharding rules.

Model code names array dimensions with *logical* axes ("batch", "embed",
"heads", ...); a rule table maps each logical axis to zero or more mesh
axes. Changing the parallelism strategy = changing the table, not the
model. (Same design as t5x/flax partitioning — the idiomatic JAX way to
express what the reference delegates to torch DDP/FSDP/vLLM.)
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
LogicalAxisRules = dict[str, object]

DEFAULT_RULES: LogicalAxisRules = {
    # activations: batch shards across slices (dcn) then within-slice dp
    "batch": ("dcn", "dp", "fsdp"),
    "seq": "sp",
    "embed_act": None,
    # params: fsdp shards the embed dim (ZeRO-3); tp shards heads/mlp/vocab
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    # Embedding-table vocab dim: REPLICATED. Sharding it over tp makes
    # params["embed"][tokens] a cross-shard gather that XLA can only
    # partition by full rematerialization (replicate-at-runtime anyway,
    # VERDICT weak #6); replicating up front costs the same memory and
    # removes the per-step reshard. lm_head keeps "vocab"→tp — the logits
    # matmul DOES partition well.
    "vocab_in": None,
    "layers": "pp",
    "experts": "ep",
    "expert_mlp": "tp",
    "kv_seq": "sp",
    "norm": None,
}


def spec_for(logical_axes: tuple[str | None, ...], rules: LogicalAxisRules) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec."""
    mesh_axes = []
    used: set[str] = set()
    for name in logical_axes:
        axis = rules.get(name) if name else None
        # a mesh axis may appear only once in a spec; later repeats replicate
        if isinstance(axis, tuple):
            axis = tuple(a for a in axis if a not in used) or None
            if isinstance(axis, tuple) and len(axis) == 1:
                axis = axis[0]
        if isinstance(axis, str) and axis in used:
            axis = None
        if axis is None:
            mesh_axes.append(None)
        else:
            for a in axis if isinstance(axis, tuple) else (axis,):
                used.add(a)
            mesh_axes.append(axis)
    while mesh_axes and mesh_axes[-1] is None:
        mesh_axes.pop()
    return P(*mesh_axes)


def logical_sharding(
    mesh: Mesh,
    logical_axes: tuple[str | None, ...],
    rules: LogicalAxisRules | None = None,
) -> NamedSharding:
    """NamedSharding for an array whose dims carry the given logical axes."""
    return NamedSharding(mesh, spec_for(logical_axes, rules or DEFAULT_RULES))


def shard_constraint(x, mesh: Mesh, logical_axes, rules=None):
    """``with_sharding_constraint`` by logical axes — use inside jit."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, tuple(logical_axes), rules)
    )


def shard_params(params, axes_tree, mesh: Mesh, rules=None):
    """Device-put a param pytree according to a matching tree of logical-axes
    tuples. ``axes_tree`` must have the same structure as ``params``."""
    shardings = jax.tree.map(
        lambda axes: logical_sharding(mesh, tuple(axes), rules),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    return jax.device_put(params, shardings)


def sharding_tree(axes_tree, mesh: Mesh, rules=None):
    """Tree of NamedShardings from a tree of logical-axes tuples (for use as
    jit in_shardings/out_shardings)."""
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, tuple(axes), rules),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def unshard(x):
    """Gather a (possibly sharded) array fully onto the host."""
    return jax.device_get(x)
