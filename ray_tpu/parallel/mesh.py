"""Device-mesh construction with named parallelism axes.

Axis vocabulary (every downstream component uses these names):

- ``dp``   — data parallel: replicate params, shard batch. Gradient psum.
- ``fsdp`` — fully-sharded data parallel (ZeRO-3): shard params *and* batch;
  all-gather params per layer, reduce-scatter grads.
- ``tp``   — tensor parallel (Megatron-style): shard attention heads and MLP
  hidden dim; all-reduce activations at block boundaries.
- ``sp``   — sequence/context parallel: shard the sequence axis; ring
  attention moves KV blocks around the ring (SURVEY.md §5.7 — green-field,
  the reference has no equivalent).
- ``pp``   — pipeline parallel: shard layers into stages.
- ``ep``   — expert parallel: shard MoE experts.
- ``dcn``  — multi-slice data parallel: the outermost axis spans TPU
  slices connected over the data-center network. Only per-step gradient
  all-reduces cross it; everything latency-bound stays on ICI inside a
  slice (the scaling-book multi-slice recipe).

The reference delegates TP/PP/EP to vLLM via placement-group GPU bundles
(``vllm_models.py:117-168``); here they are first-class mesh axes and XLA
inserts the collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "sp", "ep", "tp")
# tp innermost: tensor-parallel collectives are per-layer and latency-bound,
# so they must ride the fastest ICI links (adjacent devices); dcn/pp/dp
# outermost, their collectives are per-step and bandwidth-tolerant — dcn
# traffic crosses slices over the data-center network.


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis. -1 on at most one axis means
    "absorb all remaining devices"."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    dcn: int = 1

    def sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshConfig":
        """Fill in a single -1 axis so the product equals ``n_devices``."""
        sizes = self.sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} available"
            )
        return MeshConfig(**sizes)


def mesh_shape_for(n_devices: int, config: MeshConfig | None = None) -> MeshConfig:
    """Resolve a config against a device count; default is pure data parallel."""
    config = config or MeshConfig(dp=-1)
    return config.resolve(n_devices)


def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over all (or given) devices.

    Device order: JAX's default device list already follows the physical
    torus enumeration on TPU, so a reshape keeps tp-adjacent devices
    physically adjacent on ICI. For multi-slice, set ``MeshConfig.dcn``:
    the dcn axis is aligned to slice boundaries (hybrid mesh) so only its
    per-step gradient sync crosses the data-center network.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = mesh_shape_for(len(devices), config)
    sizes = config.sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if sizes["dcn"] > 1 and hasattr(devices[0], "slice_index"):
        # Real multi-slice pod: group devices by slice so the dcn axis is
        # EXACTLY the slice boundary. Shapes must be same-rank (per-axis
        # split between ICI and DCN); a rank mismatch would make np.block
        # concatenate slices along the innermost axis and silently put
        # latency-bound collectives on DCN. Config errors (e.g. dcn !=
        # number of slices) propagate — a misaligned fallback mesh would
        # be an order-of-magnitude silent regression.
        from jax.experimental import mesh_utils

        ici_shape = tuple(1 if a == "dcn" else sizes[a] for a in AXIS_ORDER)
        dcn_shape = tuple(sizes["dcn"] if a == "dcn" else 1 for a in AXIS_ORDER)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices,
        )
        return Mesh(dev_array, AXIS_ORDER)
    # Single slice / virtual devices (no slice_index): plain torus reshape.
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def local_mesh(config: MeshConfig | None = None) -> Mesh:
    """Mesh over this process's addressable devices only."""
    return create_mesh(config, devices=jax.local_devices())
