"""Parallelism layer: device meshes, logical-axis sharding, collectives.

This is the TPU-native replacement for the reference's NCCL/Gloo stack
(``python/ray/util/collective/collective.py:123-625``) and the parallelism
strategies it delegates to vLLM/torch (SURVEY.md §2.5). Instead of
user-space collectives, tensor communication is compiled into XLA programs:
the framework's job is to pick a ``jax.sharding.Mesh``, annotate arrays
with logical-axis shardings, and let XLA insert ICI/DCN collectives.
"""

from .mesh import (
    MeshConfig,
    create_mesh,
    local_mesh,
    mesh_shape_for,
)
from .sharding import (
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_sharding,
    shard_constraint,
    shard_params,
    unshard,
)
from .collectives import (
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    ppermute,
    reduce_scatter,
)

__all__ = [
    "MeshConfig",
    "create_mesh",
    "local_mesh",
    "mesh_shape_for",
    "LogicalAxisRules",
    "DEFAULT_RULES",
    "logical_sharding",
    "shard_constraint",
    "shard_params",
    "unshard",
    "all_gather",
    "all_reduce",
    "all_to_all",
    "barrier",
    "broadcast",
    "ppermute",
    "reduce_scatter",
]
