"""Pipeline parallelism: microbatched stage execution over the ``pp`` axis.

The reference delegates pipeline parallelism to vLLM GPU workers
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:117-168``);
here it is TPU-native: layers are sharded into ``pp`` stages, activations
flow stage→stage over ICI via ``lax.ppermute`` inside ``shard_map``, and a
``lax.scan`` over pipeline ticks runs the classic microbatch schedule —
tick t computes every stage in parallel on its current microbatch, then
rotates. The forward is GPipe-shaped with bubble (pp-1)/(n_micro+pp-1);
because the schedule is a differentiable scan, autodiff yields the
interleaved backward (the 1F1B-equivalent compute order under XLA's
scheduling) without a hand-written backward pass.

Composes with dp/fsdp on the batch axes; combine with ep for MoE stages.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    block_fn: Callable,
    stage_params,
    x,
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = "pp",
    batch_axes: tuple = ("dcn", "dp", "fsdp"),
    param_specs=None,
):
    """Run stacked layers split into ``pp`` stages over microbatches.

    block_fn(carry, layer) -> carry   — one decoder block, pure per-device
    stage_params — pytree with leading dim [n_layers] (sharded over ``pp``)
    x            — [B, S, E] activations (batch sharded over ``batch_axes``)

    Returns [B, S, E] after all layers.
    """
    pp = mesh.shape[axis]
    if pp == 1:
        def scan_body(carry, layer):
            return block_fn(carry, layer), None

        out, _ = lax.scan(scan_body, x, stage_params)
        return out

    b, s, e = x.shape
    # the requirement is on the PER-DEVICE batch shard, not the global one
    shard = 1
    for a in batch_axes:
        shard *= mesh.shape.get(a, 1)
    if (b // shard) % n_microbatches or b % shard:
        raise ValueError(
            f"per-device batch {b}/{shard}={b / shard:g} must be divisible by "
            f"{n_microbatches} microbatches (global batch {b}, batch axes {batch_axes})"
        )

    def per_device(params_local, x_local):
        """Runs on one device: params_local has this stage's layers
        [L/pp, ...]; x_local is this device's batch shard."""
        stage = lax.axis_index(axis)
        bl = x_local.shape[0]
        mbl = bl // n_microbatches
        micro = x_local.reshape(n_microbatches, mbl, *x_local.shape[1:])

        def apply_stage(act):
            def body(carry, layer):
                return block_fn(carry, layer), None

            out, _ = lax.scan(body, act, params_local)
            return out

        n_ticks = n_microbatches + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            state, outputs = carry  # state: [mbl, S, E] current activation
            # stage 0 ingests microbatch t (garbage after the last one —
            # masked out by the output gather below)
            inject = micro[jnp.minimum(t, n_microbatches - 1)]
            state = jnp.where(stage == 0, inject, state)
            state = apply_stage(state)
            # the last stage's result for microbatch t-(pp-1) is ready
            out_idx = t - (pp - 1)
            outputs = lax.cond(
                out_idx >= 0,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, state, jnp.maximum(out_idx, 0), axis=0
                ),
                lambda o: o,
                outputs,
            )
            # rotate stage outputs forward around the ring
            state = lax.ppermute(state, axis, perm=perm)
            return (state, outputs), None

        outputs0 = jnp.zeros((n_microbatches, mbl) + x_local.shape[1:], x_local.dtype)
        state0 = jnp.zeros((mbl,) + x_local.shape[1:], x_local.dtype)
        (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(n_ticks))
        # every stage ran the same schedule, but only the LAST stage's
        # written outputs are the true results — broadcast them to all
        # stages (mask + psum keeps it a single collective).
        outputs = lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        return outputs.reshape(bl, *x_local.shape[1:])

    # batch sharded over dp/fsdp; params' layer axis over pp (callers may
    # refine per-param specs, e.g. expert dims over ep); tp/sp must be 1
    # in the pipelined path this round.
    if param_specs is None:
        param_spec = jax.tree.map(lambda _: P(axis), stage_params)
    else:
        param_spec = param_specs
    x_spec = P(batch_axes, None, None)
    fn = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(stage_params, x)
