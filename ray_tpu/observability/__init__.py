"""Observability: end-to-end distributed tracing + cluster memory.

Dapper-style span propagation (Sigelman et al., 2010) over this
framework's task-event architecture: a ``TraceContext`` (trace id +
parent span id) rides ``TaskSpec`` and serve request metadata across
every hop — task submit → raylet lease grant → worker spawn/setup →
execute → get, and serve HTTP proxy → router queue → replica batch →
LLM engine prefill (first token) → decode. Spans are buffered in the
existing ``TaskEventBuffer`` and reach the GCS on the same flush path
as task status events; they merge into ``ray_tpu.timeline()``'s chrome
trace and are queryable via ``state.list_spans()`` / ``cli trace``.
"""

from .tracing import (
    TraceContext,
    bind,
    context_from_headers,
    current,
    current_wire,
    local_spans,
    make_span,
    new_span_id,
    new_trace_id,
    record_span,
    set_current,
    span,
    use_context,
)
from .spans import GcsSpanStore, format_trace_tree, spans_to_chrome
from .memory import (
    GcsMemoryStore,
    capture_callsite,
    classify_ref,
    format_memory_summary,
    hbm_stats,
    process_rss_bytes,
)

__all__ = [
    "TraceContext",
    "GcsSpanStore",
    "GcsMemoryStore",
    "capture_callsite",
    "classify_ref",
    "format_memory_summary",
    "hbm_stats",
    "process_rss_bytes",
    "bind",
    "context_from_headers",
    "current",
    "current_wire",
    "format_trace_tree",
    "local_spans",
    "make_span",
    "new_span_id",
    "new_trace_id",
    "record_span",
    "set_current",
    "span",
    "spans_to_chrome",
    "use_context",
]
