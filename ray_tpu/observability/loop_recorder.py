"""Tick-level stall attribution + per-request flight recording.

Two fixed-size, allocation-free-on-the-hot-path recorders for the paths
PRs 8/15 made invisible to the RPC/TaskEvent observability stack:

* :class:`StallRing` — lives inside a resident compiled-loop stage
  process (``dag/loop.py::_loop_tick``) and records, per tick, how the
  wall time split between waiting on upstream input (``wait_up``),
  computing (``compute``), and waiting on downstream credits
  (``wait_down``). The ring is preallocated (three ``array('d')``
  buffers); recording is three float stores and an integer increment.
  Aggregation leaves the process only on the existing periodic span
  cadence (``dag_loop_span_every``) — never per tick.

* :class:`RequestTimeline` — one per engine request, always-on: a
  bounded event log (admission, prefix hit, COW fork, prefill chunks,
  first token, per-token ITL, speculation rounds, shed/deadline,
  migration, retire) in preallocated arrays, ~hundreds of bytes per
  request. On SLO breach the whole timeline dumps once as a
  ``llm.request_timeline`` span payload.

Neither recorder ever raises into the recorded path.
"""

from __future__ import annotations

import threading
import time
from array import array

# ----------------------------------------------------------- stall attribution

#: Phase order inside one tick; also the ``bucket`` tag values of the
#: ``ray_tpu_dag_loop_tick_ms`` histogram.
STALL_BUCKETS = ("wait_up", "compute", "wait_down")
WAIT_UP, COMPUTE, WAIT_DOWN = 0, 1, 2

#: Millisecond-scale boundaries tuned for tick phases (ticks run µs–ms;
#: the default LATENCY_MS_BOUNDARIES start at 1ms and would collapse a
#: healthy loop into one bucket).
TICK_MS_BOUNDARIES = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
    50.0, 100.0, 500.0,
)


class StallRing:
    """Fixed-size per-stage ring of (wait_up, compute, wait_down) tick
    splits, in milliseconds. Written by exactly one thread (the resident
    tick executor); snapshots tolerate torn reads (diagnostic data)."""

    __slots__ = ("capacity", "ticks", "_flushed", "_ms", "totals_ms",
                 "last_file_ts")

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self.ticks = 0          # total ticks ever recorded
        self._flushed = 0       # ticks already drained to the histogram
        self._ms = tuple(array("d", bytes(8 * self.capacity))
                         for _ in range(3))
        self.totals_ms = array("d", (0.0, 0.0, 0.0))
        # monotonic stamp of the last snapshot-file write (owned by the
        # flusher in dag/loop.py; lives here so it resets with the ring)
        self.last_file_ts = 0.0

    def record(self, wait_up_ms: float, compute_ms: float,
               wait_down_ms: float) -> None:
        i = self.ticks % self.capacity
        ms = self._ms
        ms[WAIT_UP][i] = wait_up_ms
        ms[COMPUTE][i] = compute_ms
        ms[WAIT_DOWN][i] = wait_down_ms
        t = self.totals_ms
        t[WAIT_UP] += wait_up_ms
        t[COMPUTE] += compute_ms
        t[WAIT_DOWN] += wait_down_ms
        self.ticks += 1

    @property
    def overflowed(self) -> bool:
        """True once older ticks have been overwritten (newest-N kept)."""
        return self.ticks > self.capacity

    def drain(self) -> list[tuple[float, float, float]]:
        """Per-tick splits recorded since the previous ``drain`` (capped
        at ``capacity`` — a long flush gap keeps only the newest-N)."""
        n = min(self.ticks - self._flushed, self.capacity)
        out = []
        for k in range(self.ticks - n, self.ticks):
            i = k % self.capacity
            out.append((self._ms[WAIT_UP][i], self._ms[COMPUTE][i],
                        self._ms[WAIT_DOWN][i]))
        self._flushed = self.ticks
        return out

    def snapshot(self) -> dict:
        """Aggregate view: lifetime totals + mean split over the newest-N
        resident ticks. Plain dict so it serializes anywhere."""
        n = min(self.ticks, self.capacity)
        recent = [0.0, 0.0, 0.0]
        for k in range(self.ticks - n, self.ticks):
            i = k % self.capacity
            for p in range(3):
                recent[p] += self._ms[p][i]
        total = sum(self.totals_ms) or 1.0
        return {
            "ticks": self.ticks,
            "overflowed": self.overflowed,
            "totals_ms": {b: round(self.totals_ms[p], 3)
                          for p, b in enumerate(STALL_BUCKETS)},
            "frac": {b: round(self.totals_ms[p] / total, 4)
                     for p, b in enumerate(STALL_BUCKETS)},
            "recent_mean_ms": {b: round(recent[p] / n, 4) if n else 0.0
                               for p, b in enumerate(STALL_BUCKETS)},
        }


def classify_stage(frac: dict | None, ticks: int = 0) -> str:
    """One word for where a stage's time goes: ``compute_bound`` when
    compute dominates, ``starved`` when it mostly waits on upstream,
    ``backpressured`` when it mostly waits on downstream credits."""
    if not frac or not ticks:
        return "idle"
    if frac.get("compute", 0.0) >= 0.5:
        return "compute_bound"
    if frac.get("wait_up", 0.0) >= frac.get("wait_down", 0.0):
        return "starved"
    return "backpressured"


def classify_loop(stages: dict) -> str | None:
    """The loop's bottleneck stage: the one spending the largest
    fraction of its time computing — everyone else is waiting on it
    (directly or through credit backpressure)."""
    best, best_frac = None, -1.0
    for name, st in stages.items():
        frac = (st.get("frac") or {}).get("compute", 0.0)
        if st.get("ticks") and frac > best_frac:
            best, best_frac = name, frac
    return best


# In-process registry: (loop_id, stage) -> StallRing, so a stage actor
# hosting several sequential loops over its lifetime keeps them apart.
_rings_lock = threading.Lock()
_rings: dict[tuple[str, str], StallRing] = {}
_RINGS_MAX = 64  # a stage process hosts few loops; bound leakage anyway


def get_stall_ring(loop_id: str, stage: str,
                   capacity: int = 256) -> StallRing:
    key = (loop_id, stage)
    with _rings_lock:
        ring = _rings.get(key)
        if ring is None:
            if len(_rings) >= _RINGS_MAX:
                _rings.pop(next(iter(_rings)))
            ring = _rings[key] = StallRing(capacity)
        return ring


def stall_snapshots(loop_id: str) -> dict[str, dict]:
    """All of this process's stage snapshots for one loop."""
    with _rings_lock:
        items = [(k[1], r) for k, r in _rings.items() if k[0] == loop_id]
    return {stage: ring.snapshot() for stage, ring in items}


# ------------------------------------------------------ request flight recorder

EV_ADMIT = 1          # value: prompt length
EV_SHED = 2           # value: 0=queue_full 1=admission
EV_PREFIX_HIT = 3     # value: cached prefix tokens served from the trie
EV_COW_FORK = 4       # value: partial tail length forked
EV_PREFILL_CHUNK = 5  # value: tokens prefilled by this chunk
EV_FIRST_TOKEN = 6    # value: tokens prefilled in total
EV_TOKEN = 7          # value: generated-so-far (ITL = delta to prev event)
EV_SPEC_ROUND = 8     # value: tokens accepted this speculation round
EV_DEADLINE = 9       # value: generated tokens at expiry
EV_MIGRATE = 10       # value: prompt tokens imported from a peer's KV
EV_RETIRE = 11        # value: total generated tokens

EVENT_NAMES = {
    EV_ADMIT: "admit", EV_SHED: "shed", EV_PREFIX_HIT: "prefix_hit",
    EV_COW_FORK: "cow_fork", EV_PREFILL_CHUNK: "prefill_chunk",
    EV_FIRST_TOKEN: "first_token", EV_TOKEN: "token",
    EV_SPEC_ROUND: "spec_round", EV_DEADLINE: "deadline_expired",
    EV_MIGRATE: "kv_migrate_in", EV_RETIRE: "retire",
}


class RequestTimeline:
    """Bounded per-request event log: preallocated code/time/value
    arrays, circular overwrite keeping the newest-N (the head of the
    story — admission, prefix hit, first token — matters most, so those
    early one-shot events are also mirrored into ``pinned``)."""

    __slots__ = ("capacity", "_codes", "_times", "_values", "n",
                 "dumped", "_pinned")

    #: Event codes worth keeping even after the ring laps them: the
    #: request's shape is unreadable without its opening acts.
    PIN = frozenset((EV_ADMIT, EV_PREFIX_HIT, EV_MIGRATE, EV_FIRST_TOKEN))

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._codes = array("B", bytes(self.capacity))
        self._times = array("d", bytes(8 * self.capacity))
        self._values = array("i", bytes(4 * self.capacity))
        self.n = 0
        self.dumped = False
        self._pinned: list[tuple[int, float, int]] = []

    def add(self, code: int, value: int = 0, now: float | None = None) -> None:
        i = self.n % self.capacity
        t = time.time() if now is None else now
        self._codes[i] = code
        self._times[i] = t
        v = int(value)
        self._values[i] = v if -2**31 <= v < 2**31 else 0
        self.n += 1
        if code in self.PIN and len(self._pinned) < 8:
            self._pinned.append((code, t, v))

    @property
    def overflowed(self) -> bool:
        return self.n > self.capacity

    def nbytes(self) -> int:
        """Recorder storage (the preallocated arrays) — the number the
        1k-concurrent-requests byte-budget test bounds."""
        return (self._codes.itemsize * self.capacity
                + self._times.itemsize * self.capacity
                + self._values.itemsize * self.capacity)

    def events(self) -> list[dict]:
        """Oldest→newest surviving events; lapped pinned events (admit,
        prefix hit, first token) are re-prepended so a dumped timeline
        always reads admission→…→terminal."""
        n = min(self.n, self.capacity)
        start = self.n - n
        out = []
        if self.overflowed:
            kept = {(self._codes[k % self.capacity],
                     self._times[k % self.capacity])
                    for k in range(start, self.n)}
            for code, t, v in self._pinned:
                if (code, t) not in kept:
                    out.append({"ev": EVENT_NAMES.get(code, code),
                                "t": t, "v": v, "pinned": True})
        for k in range(start, self.n):
            i = k % self.capacity
            out.append({"ev": EVENT_NAMES.get(self._codes[i],
                                              int(self._codes[i])),
                        "t": self._times[i], "v": self._values[i]})
        return out

    def to_payload(self) -> dict:
        """Span-attrs payload for the ``llm.request_timeline`` dump."""
        evs = self.events()
        return {
            "events": evs,
            "n_events": self.n,
            "dropped": max(0, self.n - self.capacity),
            "overflowed": self.overflowed,
            "start": evs[0]["t"] if evs else 0.0,
            "end": evs[-1]["t"] if evs else 0.0,
        }
