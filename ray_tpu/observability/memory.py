"""Cluster memory observability: reference debugging + accounting.

The space-side sibling of ``tracing.py`` (which made *time* observable).
Three layers, mirroring the reference's ``ray memory`` /
``memory_summary()`` surfaces:

  * **reference debugging** — every user-facing ``ObjectRef`` records a
    Python creation callsite (``capture_callsite``); the owner's
    ``ReferenceCounter`` classifies each entry
    (``LOCAL_REFERENCE`` / ``USED_BY_PENDING_TASK`` /
    ``CAPTURED_IN_OBJECT`` / ``ACTOR_HANDLE`` / ``PINNED_IN_STORE``) and
    per-worker summaries ride the existing TaskEventBuffer→GCS flush
    (status ``MEMORY``) into ``GcsMemoryStore``, queryable via
    ``state.memory_summary()`` / ``cli memory`` / ``/api/memory``.
  * **node accounting** — helpers for per-process RSS and JAX HBM
    ``memory_stats()`` the raylet folds into heartbeats and
    ``debug_state_*.txt`` (``ray_tpu_object_store_*`` /
    ``ray_tpu_hbm_*`` gauges).
  * **leak detection** — ``GcsMemoryStore.detect_leaks`` flags monotonic
    growth of a worker's refcount table (or a raylet's pinned bytes)
    across N report intervals; the GCS turns suspects into diagnostics
    ``ErrorEvent``s naming the top holders by callsite (ROADMAP 1c:
    tracing alone cannot root-cause a leak — pair it with resource
    accounting, Dapper + Monarch).
"""

from __future__ import annotations

import os
import sys
import threading
import time

# Ref-type classification (reference ``ray memory`` reference types,
# ``python/ray/util/memory.py``).
LOCAL_REFERENCE = "LOCAL_REFERENCE"
USED_BY_PENDING_TASK = "USED_BY_PENDING_TASK"
CAPTURED_IN_OBJECT = "CAPTURED_IN_OBJECT"
ACTOR_HANDLE = "ACTOR_HANDLE"
PINNED_IN_STORE = "PINNED_IN_STORE"
BORROWED = "BORROWED"


# ------------------------------------------------------------- callsites
def _creation_sites_enabled() -> bool:
    try:
        from ..core.config import get_config

        return bool(get_config().record_ref_creation_sites)
    except Exception:
        return True


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep


def capture_callsite(skip: int = 1) -> str:
    """The first stack frame OUTSIDE ray_tpu, as ``file.py:line in fn``
    — the user line that created the ref (reference
    ``record_ref_creation_sites``). Returns "" when disabled."""
    if not _creation_sites_enabled():
        return ""
    try:
        frame = sys._getframe(skip)
    except ValueError:
        return ""
    while frame is not None:
        filename = frame.f_code.co_filename
        if not filename.startswith(_PKG_DIR):
            return (f"{os.path.basename(filename)}:{frame.f_lineno} "
                    f"in {frame.f_code.co_name}")
        frame = frame.f_back
    return ""


def classify_ref(*, local: int, submitted: int, contained_in: int,
                 borrowers: int, pinned: bool) -> str:
    """One reference-count shape → one ``ray memory`` ref type. Priority
    matches the reference: a ref both held locally and consumed by an
    in-flight task reads USED_BY_PENDING_TASK until the task settles."""
    if submitted > 0:
        return USED_BY_PENDING_TASK
    if contained_in > 0:
        return CAPTURED_IN_OBJECT
    if local > 0:
        return LOCAL_REFERENCE
    if borrowers > 0:
        return BORROWED
    return PINNED_IN_STORE if pinned else LOCAL_REFERENCE


# --------------------------------------------------------- node accounting
def process_rss_bytes(pid: int | None = None) -> int:
    """Resident set size of ``pid`` (default: this process) from
    ``/proc/<pid>/statm``; 0 if unreadable (dead pid, non-Linux)."""
    try:
        with open(f"/proc/{pid or os.getpid()}/statm") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, IndexError, ValueError):
        return 0


def hbm_stats() -> dict:
    """Aggregate JAX ``device.memory_stats()`` over local devices:
    ``{"used", "limit", "peak", "devices"}``. Strictly passive — never
    imports jax or initializes a backend (that would claim the TPU from
    a process that must stay off it); reports zeros until some code in
    this process has brought a backend up."""
    out = {"used": 0, "limit": 0, "peak": 0, "devices": 0}
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return out  # backend not initialized: looking would initialize it
    try:
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            out["used"] += int(ms.get("bytes_in_use", 0))
            out["limit"] += int(ms.get("bytes_limit", 0))
            out["peak"] += int(ms.get("peak_bytes_in_use",
                                      ms.get("bytes_in_use", 0)))
            out["devices"] += 1
    except Exception:
        pass
    return out


# ----------------------------------------------------------- GCS retention
class GcsMemoryStore:
    """GCS-side retention of per-worker memory summaries plus the trend
    history the leak watcher scans (the accounting half of the
    Monarch-style model: gauges for state, histories for drift)."""

    def __init__(self, history: int = 64, stale_after_s: float = 30.0):
        self._lock = threading.Lock()
        self._workers: dict[str, dict] = {}  # worker_id -> latest summary
        # worker_id -> [(ts, num_refs, total_bytes), ...] bounded
        self._history: dict[str, list[tuple]] = {}
        # node_id -> [(ts, pinned_bytes), ...] bounded (fed from heartbeats)
        self._node_history: dict[str, list[tuple]] = {}
        self._reported: set[str] = set()  # keys already flagged as leaking
        self._max_history = history
        self._stale_after = stale_after_s
        self.leaks_flagged_total = 0

    def report(self, summary: dict) -> None:
        worker_id = summary.get("worker_id", "")
        if not worker_id:
            return
        with self._lock:
            self._workers[worker_id] = summary
            hist = self._history.setdefault(worker_id, [])
            hist.append((summary.get("ts", time.time()),
                         int(summary.get("num_refs", 0)),
                         int(summary.get("total_bytes", 0))))
            del hist[: max(0, len(hist) - self._max_history)]

    def report_node(self, node_id: str, pinned_bytes: int) -> None:
        with self._lock:
            hist = self._node_history.setdefault(node_id, [])
            hist.append((time.time(), int(pinned_bytes)))
            del hist[: max(0, len(hist) - self._max_history)]

    def _prune_locked(self) -> None:
        cutoff = time.time() - self._stale_after
        for wid, s in list(self._workers.items()):
            if s.get("ts", 0.0) < cutoff:
                del self._workers[wid]
                self._history.pop(wid, None)
                self._reported.discard("worker:" + wid)

    def summary(self) -> dict:
        """The merged cluster view behind ``state.memory_summary()``."""
        with self._lock:
            self._prune_locked()
            workers = [dict(s) for s in self._workers.values()]
        workers.sort(key=lambda s: s.get("total_bytes", 0), reverse=True)
        return {
            "ts": time.time(),
            "num_workers": len(workers),
            "total_bytes": sum(s.get("total_bytes", 0) for s in workers),
            "num_refs": sum(s.get("num_refs", 0) for s in workers),
            "workers": workers,
        }

    def size(self) -> int:
        with self._lock:
            return len(self._workers)

    def hbm_by_node(self) -> dict[str, dict]:
        """Per-node HBM view from worker reports: max across a node's
        workers (the device lock is exclusive per process, and max never
        double-counts a driver that shares the raylet's process)."""
        out: dict[str, dict] = {}
        with self._lock:
            reports = list(self._workers.values())
        for s in reports:
            hbm = s.get("hbm") or {}
            node = s.get("node_id", "")
            cur = out.setdefault(node, {"used": 0, "limit": 0, "peak": 0})
            for k in cur:
                cur[k] = max(cur[k], int(hbm.get(k, 0)))
        return out

    @staticmethod
    def _monotonic_growth(hist: list[tuple], intervals: int,
                          value_index: int) -> int:
        """Total growth when the last ``intervals`` deltas of
        ``hist[value_index]`` are all positive, else 0."""
        if len(hist) < intervals + 1:
            return 0
        window = hist[-(intervals + 1):]
        deltas = [window[i + 1][value_index] - window[i][value_index]
                  for i in range(intervals)]
        if all(d > 0 for d in deltas):
            return sum(deltas)
        return 0

    def detect_leaks(self, intervals: int = 4,
                     min_growth_bytes: int = 1 << 20,
                     min_growth_refs: int = 50,
                     top_k: int = 5) -> list[dict]:
        """Suspects whose refcount table / byte total / pinned bytes grew
        monotonically across the last ``intervals`` reports. Each suspect
        fires once; flat-or-shrinking history re-arms it."""
        suspects: list[dict] = []
        with self._lock:
            self._prune_locked()
            for wid, hist in self._history.items():
                key = "worker:" + wid
                ref_growth = self._monotonic_growth(hist, intervals, 1)
                byte_growth = self._monotonic_growth(hist, intervals, 2)
                if ref_growth < min_growth_refs and byte_growth < min_growth_bytes:
                    self._reported.discard(key)
                    continue
                if key in self._reported:
                    continue
                self._reported.add(key)
                self.leaks_flagged_total += 1
                latest = self._workers.get(wid, {})
                suspects.append({
                    "kind": "worker_refs",
                    "worker_id": wid,
                    "node_id": latest.get("node_id", ""),
                    "growth_refs": ref_growth,
                    "growth_bytes": byte_growth,
                    "num_refs": latest.get("num_refs", 0),
                    "total_bytes": latest.get("total_bytes", 0),
                    "top_holders": _top_holders(latest.get("entries") or [],
                                                top_k),
                })
            for node_id, hist in self._node_history.items():
                key = "node:" + node_id
                growth = self._monotonic_growth(hist, intervals, 1)
                if growth < min_growth_bytes:
                    self._reported.discard(key)
                    continue
                if key in self._reported:
                    continue
                self._reported.add(key)
                self.leaks_flagged_total += 1
                suspects.append({
                    "kind": "node_pinned_bytes",
                    "node_id": node_id,
                    "growth_bytes": growth,
                    "pinned_bytes": hist[-1][1],
                    "top_holders": [],
                })
        return suspects


def _top_holders(entries: list[dict], top_k: int) -> list[dict]:
    """Aggregate a summary's entries by creation callsite, biggest first
    — the "who is holding this and why" line of the leak report."""
    by_site: dict[str, dict] = {}
    for e in entries:
        site = e.get("callsite") or "(callsite unknown)"
        agg = by_site.setdefault(site, {"callsite": site, "count": 0,
                                        "bytes": 0, "ref_types": set()})
        agg["count"] += 1
        agg["bytes"] += int(e.get("size", 0))
        agg["ref_types"].add(e.get("ref_type", ""))
    out = sorted(by_site.values(), key=lambda a: (a["bytes"], a["count"]),
                 reverse=True)[:top_k]
    for agg in out:
        agg["ref_types"] = sorted(agg["ref_types"])
    return out


def leak_event_message(suspect: dict) -> str:
    """Human line for the diagnostics ErrorEvent."""
    if suspect.get("kind") == "node_pinned_bytes":
        return (f"possible object-store leak on node "
                f"{suspect.get('node_id', '')[:8]}: pinned bytes grew "
                f"{suspect.get('growth_bytes', 0)}B monotonically "
                f"(now {suspect.get('pinned_bytes', 0)}B)")
    holders = "; ".join(
        f"{h['callsite']} ({h['count']} refs, {h['bytes']}B)"
        for h in suspect.get("top_holders") or [])
    return (f"possible reference leak in worker "
            f"{suspect.get('worker_id', '')[:12]}: +{suspect.get('growth_refs', 0)} "
            f"refs / +{suspect.get('growth_bytes', 0)}B over the watch window "
            f"({suspect.get('num_refs', 0)} refs, "
            f"{suspect.get('total_bytes', 0)}B held). "
            f"Top holders: {holders or '(no callsites recorded)'}")


def format_memory_summary(summary: dict, nodes: list[dict] | None = None) -> str:
    """``cli memory`` rendering: per-node store/HBM header then a
    per-worker object table (object id, size, ref type, age, callsite) —
    the shape of the reference's ``ray memory`` output."""
    lines: list[str] = []
    for n in nodes or []:
        if n.get("state") != "ALIVE":
            continue
        store = n.get("store") or {}
        hbm = n.get("hbm") or {}
        lines.append(
            "node %s  store %s/%s B (pinned %s, spilled %s B)  hbm %s/%s B" % (
                n.get("node_id", "")[:12],
                store.get("used", 0),
                store.get("capacity", n.get("object_store_capacity", 0)),
                store.get("pinned_bytes", 0),
                store.get("spilled_bytes_total", 0),
                hbm.get("used", 0), hbm.get("limit", 0)))
    lines.append("%d workers, %d refs, %d bytes tracked" % (
        summary.get("num_workers", 0), summary.get("num_refs", 0),
        summary.get("total_bytes", 0)))
    header = ("OBJECT_ID", "SIZE", "REF_TYPE", "AGE_S", "CALLSITE")
    fmt = "%-28s %10s %-22s %8s  %s"
    for w in summary.get("workers") or []:
        lines.append("")
        lines.append("worker %s (node %s): %s refs, %s bytes" % (
            w.get("worker_id", "")[:12], w.get("node_id", "")[:8],
            w.get("num_refs", 0), w.get("total_bytes", 0)))
        lines.append(fmt % header)
        for e in w.get("entries") or []:
            lines.append(fmt % (
                e.get("object_id", "")[:28], e.get("size", 0),
                e.get("ref_type", ""), round(e.get("age_s", 0.0), 1),
                e.get("callsite", "")))
    return "\n".join(lines)
