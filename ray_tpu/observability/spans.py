"""GCS-side span retention and rendering.

``GcsSpanStore`` keeps a bounded, trace-keyed log of finished spans
(the span half of ``GcsTaskManager``): workers flush spans through
``AddTaskEvents`` (status ``SPAN``) and the GCS routes them here. The
store powers ``state.list_spans()`` / ``cli trace`` and merges into the
chrome trace that ``ray_tpu.timeline()`` dumps.
"""

from __future__ import annotations

import threading


class GcsSpanStore:
    """Bounded span log aggregated per trace; whole-trace eviction in
    insertion order once the global span cap is hit."""

    def __init__(self, max_spans: int = 20_000):
        self._lock = threading.Lock()
        self._traces: dict[str, list[dict]] = {}  # insertion order = age
        self._total = 0
        self._max = max_spans
        self.num_dropped = 0

    def add(self, spans: list[dict]) -> None:
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    self.num_dropped += 1
                    continue
                while self._total >= self._max and self._traces:
                    oldest = next(iter(self._traces))
                    if oldest == tid and len(self._traces) == 1:
                        break  # never evict the trace we are appending to
                    evicted = self._traces.pop(oldest)
                    self._total -= len(evicted)
                    self.num_dropped += len(evicted)
                self._traces.setdefault(tid, []).append(s)
                self._total += 1

    def size(self) -> int:
        with self._lock:
            return self._total

    def list_spans(self, trace_id: str | None = None, limit: int = 1000) -> list[dict]:
        with self._lock:
            if trace_id:
                out = list(self._traces.get(trace_id, []))
            else:
                out = [s for spans in self._traces.values() for s in spans]
        out.sort(key=lambda s: s.get("start", 0.0))
        return out[-limit:]

    def list_traces(self, limit: int = 100) -> list[dict]:
        """Per-trace summaries, most recent last."""
        rows = []
        with self._lock:
            items = list(self._traces.items())[-limit:]
        for tid, spans in items:
            start = min(s.get("start", 0.0) for s in spans)
            end = max(s.get("end", 0.0) for s in spans)
            ids = {s["span_id"] for s in spans}
            roots = [s for s in spans if s.get("parent_id", "") not in ids]
            root = min(roots or spans, key=lambda s: s.get("start", 0.0))
            rows.append({
                "trace_id": tid,
                "root": root.get("name", ""),
                "spans": len(spans),
                "start": start,
                "duration_ms": round((end - start) * 1000.0, 3),
            })
        return rows

    def chrome_trace(self) -> list[dict]:
        with self._lock:
            spans = [s for group in self._traces.values() for s in group]
        return spans_to_chrome(spans)


def spans_to_chrome(spans: list[dict]) -> list[dict]:
    """Chrome-trace slices + flow arrows for a span set. Each trace gets
    its own process row; within it spans group by (kind, recording
    worker), where parent/child spans nest by time on the shared track.
    Parent→child links are drawn as flow events keyed by the child span
    id so the serve request path reads as one connected tree."""
    trace: list[dict] = []
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        pid = f"trace:{s['trace_id'][:8]}"
        tid = f"{s.get('kind', 'span')}:{(s.get('worker_id') or '?')[:8]}"
        ts = s.get("start", 0.0) * 1e6
        dur = max(1.0, (s.get("end", 0.0) - s.get("start", 0.0)) * 1e6)
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                "parent_id": s.get("parent_id", "")}
        args.update(s.get("attrs") or {})
        trace.append({
            "name": s.get("name", "span"), "cat": "span", "ph": "X",
            "ts": ts, "dur": dur, "pid": pid, "tid": tid, "args": args,
        })
        parent = by_id.get(s.get("parent_id", ""))
        if parent is not None:
            flow_id = int(s["span_id"][:12], 16)
            ppid = f"trace:{parent['trace_id'][:8]}"
            ptid = f"{parent.get('kind', 'span')}:{(parent.get('worker_id') or '?')[:8]}"
            trace.append({"name": "span_link", "cat": "span_flow", "ph": "s",
                          "id": flow_id, "ts": parent.get("start", 0.0) * 1e6,
                          "pid": ppid, "tid": ptid})
            trace.append({"name": "span_link", "cat": "span_flow", "ph": "f",
                          "bp": "e", "id": flow_id, "ts": ts,
                          "pid": pid, "tid": tid})
    return trace


def format_trace_tree(spans: list[dict]) -> str:
    """ASCII tree of one trace's spans for ``cli trace <id>``."""
    if not spans:
        return "(no spans)"
    ids = {s["span_id"] for s in spans}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in sorted(spans, key=lambda s: s.get("start", 0.0)):
        parent = s.get("parent_id", "")
        if parent in ids:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    lines: list[str] = []

    def _walk(s: dict, depth: int) -> None:
        dur_ms = (s.get("end", 0.0) - s.get("start", 0.0)) * 1000.0
        where = (s.get("node_id") or "")[:8]
        lines.append(
            f"{'  ' * depth}{s.get('name', 'span')}  "
            f"[{s.get('kind', '?')}] {dur_ms:.1f}ms"
            + (f"  node={where}" if where else ""))
        for c in children.get(s["span_id"], []):
            _walk(c, depth + 1)

    for r in roots:
        _walk(r, 0)
    return "\n".join(lines)
