"""Trace-context propagation and span recording.

A span is a plain dict (msgpack-encodable so it crosses the RPC layer
untouched)::

    {"trace_id", "span_id", "parent_id", "name", "kind",
     "start", "end",              # wall-clock seconds (time.time())
     "worker_id", "node_id",      # filled at GCS ingest from the event
     "attrs": {...}}

The active context is thread-local: it is installed explicitly at every
thread hop (``bind``) and by the executor when it runs a task whose
``TaskSpec`` carries trace fields — exactly the places the reference
threads OpenTelemetry context through ``_raylet.pyx``.

Recording goes through the worker's ``TaskEventBuffer`` (status
``SPAN``) so spans share the batched GCS flush with task status events;
processes without a core worker (standalone engine in tests, the GCS
itself) fall back to a bounded process-local buffer readable via
``local_spans()``.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

TRACE_HEADER = "x-raytpu-trace"

_tls = threading.local()

_local_lock = threading.Lock()
_local_spans: list[dict] = []
_LOCAL_MAX = 4096


# Id generation is ON the task-submit hot path (one trace id + one span
# id per submit): uuid4 costs an os.urandom syscall each — ~60% of a
# 100k-no-op submit loop's wall time before PR 6. A process-seeded
# Random gives the same 128/64 bits of collision resistance for tracing
# purposes at ~30x less cost (os.urandom seeds it once; forked workers
# reseed via the pid mix so children never replay the parent's stream).
_id_rng = random.Random()
_id_rng.seed(int.from_bytes(os.urandom(16), "big") ^ os.getpid())
_id_pid = os.getpid()
_id_lock = threading.Lock()


def _id_hex(bits: int) -> str:
    global _id_pid
    with _id_lock:
        if os.getpid() != _id_pid:  # forked child: never replay the parent
            _id_rng.seed(int.from_bytes(os.urandom(16), "big") ^ os.getpid())
            _id_pid = os.getpid()
        return f"{_id_rng.getrandbits(bits):0{bits // 4}x}"


def new_trace_id() -> str:
    return _id_hex(128)


def new_span_id() -> str:
    return _id_hex(64)


@dataclass(frozen=True)
class TraceContext:
    trace_id: str
    span_id: str
    parent_id: str = ""

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, new_span_id(), self.span_id)

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}


def current() -> TraceContext | None:
    return getattr(_tls, "ctx", None)


def current_wire() -> dict | None:
    ctx = current()
    return ctx.to_wire() if ctx is not None else None


def set_current(ctx: TraceContext | None) -> TraceContext | None:
    """Install ``ctx`` as this thread's active context; returns the
    previous one so callers can restore it."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


@contextlib.contextmanager
def use_context(ctx: TraceContext | None):
    prev = set_current(ctx)
    try:
        yield ctx
    finally:
        set_current(prev)


def bind(ctx: TraceContext | None, fn: Callable, *args, **kwargs) -> Callable:
    """Wrap ``fn`` so it runs under ``ctx`` on whatever thread executes
    it (thread-locals do not survive ``run_in_executor`` hops)."""

    def _wrapped():
        prev = set_current(ctx)
        try:
            return fn(*args, **kwargs)
        finally:
            set_current(prev)

    return _wrapped


def context_from_headers(headers: dict | None) -> TraceContext:
    """Root context for an ingress request: continue an incoming
    ``x-raytpu-trace: <trace_id>:<span_id>`` header (the remote span
    becomes our parent) or start a fresh trace."""
    raw = (headers or {}).get(TRACE_HEADER, "")
    if raw and ":" in raw:
        trace_id, _, parent = raw.partition(":")
        if trace_id:
            return TraceContext(trace_id, new_span_id(), parent)
    return TraceContext(new_trace_id(), new_span_id())


def make_span(name: str, kind: str, start: float, end: float,
              trace_id: str, parent_id: str = "", span_id: str | None = None,
              attrs: dict | None = None) -> dict:
    return {
        "trace_id": trace_id,
        "span_id": span_id or new_span_id(),
        "parent_id": parent_id,
        "name": name,
        "kind": kind,
        "start": start,
        "end": end,
        "attrs": attrs or {},
    }


def _tracing_enabled() -> bool:
    try:
        from ..core.config import get_config

        return bool(get_config().enable_tracing)
    except Exception:
        return True


def record_span(span_dict: dict) -> None:
    """Buffer one finished span. Never raises — tracing must not be able
    to fail the traced operation."""
    if not span_dict.get("trace_id") or not _tracing_enabled():
        return
    try:
        from ..core.worker import _global_worker

        if _global_worker is not None:
            _global_worker.task_events.record_span(span_dict)
            return
    except Exception:
        pass
    with _local_lock:
        if len(_local_spans) >= _LOCAL_MAX:
            del _local_spans[: _LOCAL_MAX // 4]
        _local_spans.append(span_dict)


def local_spans(trace_id: str | None = None) -> list[dict]:
    """Spans recorded in this process while no core worker was connected
    (standalone engines, unit tests)."""
    with _local_lock:
        out = list(_local_spans)
    if trace_id:
        out = [s for s in out if s["trace_id"] == trace_id]
    return out


@contextlib.contextmanager
def span(name: str, kind: str = "app", attrs: dict | None = None,
         root: bool = False):
    """Record a span around a code block. Opens a child of the current
    context (or a fresh root trace when there is none or ``root=True``)
    and installs itself as the current context for the duration, so
    anything submitted inside — tasks, actor calls, engine requests —
    chains under it."""
    parent = None if root else current()
    if parent is None:
        ctx = TraceContext(new_trace_id(), new_span_id())
    else:
        ctx = parent.child()
    start = time.time()
    with use_context(ctx):
        try:
            yield ctx
        finally:
            record_span(make_span(name, kind, start, time.time(),
                                  ctx.trace_id, ctx.parent_id, ctx.span_id,
                                  attrs))
