"""Dashboard: an HTTP window onto cluster state.

Equivalent of the reference's ``dashboard/`` (head-node web UI +
``dashboard/modules/*`` REST endpoints), scoped to what a TPU-cluster
operator actually debugs with: nodes, actors, tasks, objects, workers,
placement groups, jobs, metrics, and a downloadable Perfetto timeline.
Redesign: a stdlib ThreadingHTTPServer thread inside the driver process
serving JSON from the state API — no Node.js build, no agent processes.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

def _ui_html() -> bytes:
    """The single-file SPA (``dashboard_ui.html`` next to this module —
    the reference ships a React build in ``dashboard/client/``; here one
    no-build HTML file renders the same overview pages from the JSON
    API)."""
    import os

    path = os.path.join(os.path.dirname(__file__), "dashboard_ui.html")
    with open(path, "rb") as f:
        return f.read()


_ENDPOINTS = [
    "nodes", "actors", "tasks", "objects", "workers",
    "placement_groups", "jobs", "metrics", "cluster_resources",
    "available_resources", "timeline", "grafana_dashboard",
    "errors", "diagnostics", "traces", "memory", "profiles", "loops",
]


def _collect(endpoint: str):
    from .core import api as core_api
    from .util import state

    if endpoint == "nodes":
        return state.list_nodes()
    if endpoint == "actors":
        return state.list_actors()
    if endpoint == "tasks":
        return state.list_tasks()
    if endpoint == "objects":
        return state.list_objects()
    if endpoint == "workers":
        return state.list_workers()
    if endpoint == "errors":
        return state.list_errors()
    if endpoint == "diagnostics":
        return state.cluster_diagnostics()
    if endpoint == "traces":
        return state.list_traces()
    if endpoint == "memory":
        return state.memory_summary()
    if endpoint == "profiles":
        return state.list_profiles()
    if endpoint == "loops":
        # Compiled-loop stall attribution (driver-local: the dashboard
        # thread runs in the driver, which owns the CompiledLoop objects).
        return state.loop_stats()
    if endpoint == "placement_groups":
        return state.list_placement_groups()
    if endpoint == "jobs":
        from .job.job_manager import JOB_MANAGER_NAME

        try:
            mgr = core_api.get_actor(JOB_MANAGER_NAME)
        except ValueError:
            return []
        return core_api.get(mgr.list.remote(), timeout=30)
    if endpoint == "metrics":
        from .util.metrics import get_metrics

        return get_metrics()
    if endpoint == "cluster_resources":
        return core_api.cluster_resources()
    if endpoint == "available_resources":
        return core_api.available_resources()
    if endpoint == "grafana_dashboard":
        from .grafana import generate_dashboard

        return generate_dashboard()
    if endpoint == "timeline":
        # Chrome-trace JSON, loadable in Perfetto (reference ray.timeline).
        # Unique temp file per request: ThreadingHTTPServer handles
        # requests concurrently and the trace write is not atomic.
        import os
        import tempfile

        from . import timeline as dump_timeline

        fd, path = tempfile.mkstemp(prefix="raytpu_timeline_", suffix=".json")
        os.close(fd)
        try:
            dump_timeline(path)
            with open(path) as f:
                return json.load(f)
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass
    raise KeyError(endpoint)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path in ("", "/index.html"):
            self._send(200, _ui_html(), "text/html; charset=utf-8")
            return
        if path == "/metrics":
            # Prometheus scrape endpoint (reference: per-node metrics
            # agent re-export; one process here).
            try:
                from .util.metrics import prometheus_text

                self._send(200, prometheus_text().encode(), "text/plain; version=0.0.4")
            except Exception as e:
                self._send(500, f"# error: {e}\n".encode(), "text/plain")
            return
        if path == "/-/healthz":
            self._send(200, b'"ok"', "application/json")
            return
        if path == "/api/serve/applications":
            # Serve REST status (reference dashboard serve REST API).
            try:
                from .serve.config_api import serve_status

                self._send(200, json.dumps(serve_status(), default=str).encode(),
                           "application/json")
            except Exception as e:
                self._send(500, json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                           "application/json")
            return
        if path.startswith("/api/"):
            endpoint = path[len("/api/"):]
            if endpoint not in _ENDPOINTS:
                self._send(404, json.dumps({"error": f"unknown endpoint {endpoint}"}).encode(),
                           "application/json")
                return
            try:
                data = _collect(endpoint)
                self._send(200, json.dumps(data, default=str).encode(), "application/json")
            except Exception as e:
                self._send(500, json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                           "application/json")
            return
        self._send(404, b'{"error": "not found"}', "application/json")

    def do_PUT(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path != "/api/serve/applications":
            self._send(404, b'{"error": "not found"}', "application/json")
            return
        # Declarative deploy (reference PUT /api/serve/applications/).
        try:
            length = int(self.headers.get("Content-Length", 0))
            config = json.loads(self.rfile.read(length))
            from .serve.config_api import deploy_config

            deployed = deploy_config(config)
            self._send(200, json.dumps({"deployed": deployed}).encode(),
                       "application/json")
        except Exception as e:
            self._send(500, json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                       "application/json")

    def do_DELETE(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        prefix = "/api/serve/applications/"
        if not path.startswith(prefix):
            self._send(404, b'{"error": "not found"}', "application/json")
            return
        try:
            from .serve import api as serve_api

            serve_api.delete(path[len(prefix):])
            self._send(200, b'{"deleted": true}', "application/json")
        except Exception as e:
            self._send(500, json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                       "application/json")


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="raytpu-dashboard"
        )
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


_dashboard: Dashboard | None = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start (or return) the dashboard; returns its URL."""
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard.url


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
