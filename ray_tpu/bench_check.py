"""Bench-regression guard: diff two recorded benchmark results.

``python -m ray_tpu.bench_check BENCH_r05.json BENCH_r06.json`` compares
every shared numeric metric and exits non-zero when any regresses by
more than the threshold (default 10%) — so a silent drop like the
round-5 ``flash_fwdbwd_tflops_s4096`` 26.16 → 22.99 slide, or a metric
silently VANISHING (round 5's ``serve_p50_ttft_ms``, lost to a replica
startup failure), gets flagged at PR time instead of two rounds later.

Accepts either a bare metrics object (what ``bench.py`` prints) or the
driver's ``BENCH_rNN.json`` wrapper (metrics under ``"parsed"``).

Direction is inferred from the metric name: ``*_ms`` / ``*_pct`` /
latency-like metrics regress UP, throughput-like metrics regress DOWN;
bookkeeping fields (counts, config echoes, error strings) are skipped.
``bench.py`` runs this automatically against the most recent
``BENCH_r*.json`` in the working directory (report-only — the bench
still records its numbers; CI decides what to do with the exit code).
"""

from __future__ import annotations

import glob
import json
import os
import sys

# Metrics that describe the run, not its performance. Shed/offered
# counts from the overload bench are bookkeeping: protection ON sheds
# MORE than the unprotected baseline by design, so neither direction is
# a regression — goodput_frac and the fast-fail latency are the guarded
# numbers.
_SKIP_EXACT = {
    "n", "rc", "vs_baseline", "loss", "serve_requests", "serve_concurrency",
    "serve_decode_steps_per_dispatch",
    "serve_shed_requests", "serve_overload_offered", "serve_overload_completed",
    "serve_deadline_expired",
    # Speculative-bench bookkeeping: draft volume and dispatch counts
    # describe the run; accept_rate / tokens_per_dispatch / tok_s are
    # the guarded numbers.
    "spec_drafted_tokens", "spec_dispatches",
}
# "_cfg": config echoes (core-bench phase sizes etc.) — sizes are inputs,
# not results.
_SKIP_SUBSTR = ("error", "preset", "metric", "unit", "cmd", "tail", "_cfg")
# Throughput rates: ALWAYS higher-better, checked BEFORE the lower-better
# suffixes — "core_tasks_per_s" ends in "_s" but a drop in it is the
# regression, not an improvement. "_mb_s": transfer throughput in MB/s
# (kv_migration_mb_s), same shadowed-by-"_s" hazard. "_tok_s": token
# throughput — round-13 audit found a bare "..._tok_s" metric would be
# shadowed by the lower-better "_s" exactly like "_mb_s" was before
# PR 11 (existing names only dodge it by suffixing the cell, e.g.
# decode_tok_s_plain). "_tokens_per_dispatch": speculative-decoding
# amortization (emitted tokens per slot per verify forward).
_HIGHER_BETTER_SUFFIX = ("_per_s", "_per_sec", "_mb_s", "_tok_s",
                         "_tokens_per_dispatch")
# 0-1 ratios (cache hit rates, accept rates, fractions): higher-better
# AND compared in POINTS like _pct — a hit rate sliding 0.90 -> 0.45 is
# a 45-point collapse; 0.02 -> 0.01 is noise, not a 50% regression.
# "_accept_rate": the speculative drafter's 0-1 accept fraction.
# "_frac" covers train_ckpt_overlap_frac (round 15) alongside the
# serve goodput/suffix fractions. "_parity": greedy byte-parity cells
# (spec_parity, serve_overload_parity, tenant_mixed_batch_parity) — a
# 1.0-or-broken invariant, so pointwise; any slip below 1.0 is the
# regression. Round-16 shadow audit: the new tenancy cells end in
# "_ms" (tenant_quiet_p95_ttft_ms*, adapter_hot_load_ms — lower-better,
# and "ttft" substring already matches the quiet-p95 pair), "_frac"
# (tenant_goodput_frac_* — pointwise), and "_parity"; none end in a
# bare "_s", so the pre-PR-11 "_mb_s" shadowing hazard doesn't apply.
_POINTWISE_RATE_SUFFIX = ("_hit_rate", "_accept_rate", "_frac", "_parity")
# MFU is a 0-1 fraction too, but its cell tag often FOLLOWS the unit
# ("mfu", "mfu_8b_proxy", "train_mfu_eager", "train_mfu_loop",
# "train_mfu_1b_seq8k"), so it is matched by substring, not suffix.
# "goodput_frac": same tag-after-unit shape — the round-16 audit found
# serve_goodput_frac_unprotected and tenant_goodput_frac_{hot,cold}
# fell out of the "_frac" suffix into a relative compare, where a
# CPU-sandbox 0.05 -> 0.04 wiggle reads as a 20% regression.
# Round-15 audit note: none of the mfu cells end in "_s"/"_ms", so the
# lower-better suffix table cannot shadow them (the pre-PR-11 "_mb_s"
# hazard) — but a relative compare would still flag a 0.0002-point CPU
# wiggle as a regression; points are the right scale.
_POINTWISE_RATE_SUBSTR = ("mfu", "goodput_frac")
# Round-19 shadow audit (fleet bench): ``serve_replica_promote_s`` /
# ``serve_replica_cold_start_s`` end in a bare "_s" → lower-better, the
# right call (promotion getting slower IS the regression the always-warm
# pool exists to prevent). ``fleet_broadcast_parity`` rides the
# "_parity" pointwise suffix (1.0-or-broken), ``fleet_goodput_frac_step``
# the "goodput_frac" substring (pointwise — a CPU-sandbox 0.05 wiggle
# must not read as a relative collapse), and
# ``serve_replica_promote_speedup`` falls through to the default
# higher-better. ``fleet_skipped``/per-cell ``*_skipped`` markers flow
# through _skip_prefixes like every other suite's.
# Pointwise cells that regress UP: still compared in points on the 0-1
# scale, but LOWER is better. Round-18 audit: before this table,
# ``loop_obs_overhead_frac`` (stall-recorder cost as a fraction of tick
# dispatch) fell into the pointwise branch and was guarded BACKWARDS —
# the "_frac" suffix check ran before the "overhead" substring, so a
# recorder cost blowup 0.01 -> 0.15 read as a 14-point improvement.
# "stall_wait": the dag loop's wait_up/wait_down stall split — a stage
# spending more of its tick blocked is the regression (the compute_frac
# cell stays higher-better pointwise via the plain "_frac" suffix).
_POINTWISE_DOWN_SUBSTR = ("overhead", "stall_wait")
# Lower is better. Peak-memory gauges count as regressions when they
# GROW >threshold (a quiet 2x pool blowup is exactly what they exist
# to catch). "_lag_steps": checkpoint lag (steps replayed after a
# preemption recovery) regresses UP — more lost work is worse.
# "fast_fail": the time-to-503 of a shed request (overload bench) —
# slower rejections are the regression the bound exists to prevent.
_LOWER_BETTER_SUFFIX = ("_ms", "_us", "_pct", "_bytes", "_s", "_lag_steps")
_LOWER_BETTER_SUBSTR = ("latency", "ttft", "overhead", "failed", "fast_fail")


def load_metrics(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object of metrics")
    return data


def _pointwise(name: str) -> bool:
    """0-1 fraction metrics compared in points (higher-better)."""
    return name.endswith(_POINTWISE_RATE_SUFFIX) or any(
        s in name for s in _POINTWISE_RATE_SUBSTR)


def _direction(name: str) -> str:
    """'up' = larger is better, 'down' = smaller is better."""
    if _pointwise(name):
        # Pointwise cells carry their own direction: fractions are
        # higher-better unless the name marks them as a cost/stall.
        return "down" if any(s in name for s in _POINTWISE_DOWN_SUBSTR) \
            else "up"
    if name.endswith(_HIGHER_BETTER_SUFFIX):
        return "up"
    if name.endswith(_LOWER_BETTER_SUFFIX) or any(
            s in name for s in _LOWER_BETTER_SUBSTR):
        return "down"
    return "up"


def _tracked(name: str, value) -> bool:
    if name in _SKIP_EXACT or any(s in name for s in _SKIP_SUBSTR):
        return False
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def _skip_prefixes(new: dict) -> tuple:
    """``<prefix>_skipped: true`` markers: the run declares it
    INTENTIONALLY skipped every ``<prefix>*`` metric (e.g. a serve-matrix
    cell filtered out via RAY_TPU_SERVE_MATRIX_CELLS). Such metrics are
    reported as skipped, never as silently vanished."""
    return tuple(k[: -len("_skipped")] for k, v in new.items()
                 if k.endswith("_skipped") and v)


def compare(old: dict, new: dict, threshold: float = 0.10) -> dict:
    """Returns {"regressions": [...], "improvements": [...],
    "missing": [...], "skipped": [...], "ok": [...]} — each row a dict
    with metric, old, new, change (signed fraction, + = better).
    ``skipped`` rows are absences covered by a ``*_skipped`` marker in
    the new run (intentional, non-failing)."""
    out = {"regressions": [], "improvements": [], "missing": [],
           "skipped": [], "ok": []}
    skipped = _skip_prefixes(new)
    for name, ov in sorted(old.items()):
        if not _tracked(name, ov):
            continue
        nv = new.get(name)
        if not isinstance(nv, (int, float)) or isinstance(nv, bool):
            if skipped and name.startswith(skipped):
                out["skipped"].append({"metric": name, "old": ov, "new": None})
                continue
            # was measured, now gone: exactly the silent failure mode
            # this guard exists for
            out["missing"].append({"metric": name, "old": ov, "new": None})
            continue
        if _pointwise(name):
            # 0-1 rates compare in POINTS: the threshold is a point
            # budget on the 0-1 scale (0.10 = 10 points). Direction
            # comes from the name — overhead/stall fracs regress UP.
            delta = round(nv - ov, 4)
            better = delta if _direction(name) == "up" else -delta
            row = {"metric": name, "old": ov, "new": nv, "change": better}
            if better < -threshold:
                out["regressions"].append(row)
            elif better > threshold:
                out["improvements"].append(row)
            else:
                out["ok"].append(row)
            continue
        if ov == 0:
            continue
        if name.endswith("_pct") and abs(nv - ov) < 1.0:
            # percentages compare in POINTS: -0.14% -> -0.05% framework
            # overhead is noise, not a 64% regression
            out["ok"].append({"metric": name, "old": ov, "new": nv,
                              "change": 0.0})
            continue
        delta = (nv - ov) / abs(ov)
        better = delta if _direction(name) == "up" else -delta
        row = {"metric": name, "old": ov, "new": nv,
               "change": round(better, 4)}
        if better < -threshold:
            out["regressions"].append(row)
        elif better > threshold:
            out["improvements"].append(row)
        else:
            out["ok"].append(row)
    return out


def format_report(result: dict, old_path: str = "old", new_path: str = "new",
                  threshold: float = 0.10) -> str:
    lines = [f"bench_check: {old_path} -> {new_path} "
             f"(threshold {threshold:.0%})"]
    for row in result["regressions"]:
        lines.append(f"  REGRESSION  {row['metric']}: {row['old']} -> "
                     f"{row['new']} ({row['change']:+.1%})")
    for row in result["missing"]:
        lines.append(f"  MISSING     {row['metric']}: {row['old']} -> "
                     "absent in new run")
    for row in result.get("skipped", []):
        lines.append(f"  skipped     {row['metric']}: intentionally "
                     "skipped in new run (marker present)")
    for row in result["improvements"]:
        lines.append(f"  improved    {row['metric']}: {row['old']} -> "
                     f"{row['new']} ({row['change']:+.1%})")
    n_ok = len(result["ok"])
    lines.append(f"  {n_ok} metric(s) within threshold; "
                 f"{len(result['regressions'])} regression(s), "
                 f"{len(result['missing'])} missing")
    return "\n".join(lines)


def latest_bench_json(directory: str = ".") -> str | None:
    """Most recent driver-recorded BENCH_r*.json, for bench.py's
    self-check after a run."""
    paths = sorted(glob.glob(os.path.join(directory, "BENCH_r[0-9]*.json")))
    return paths[-1] if paths else None


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    threshold = 0.10
    paths = []
    it = iter(argv)
    for a in it:
        if a == "--threshold":
            threshold = float(next(it))
        elif a.startswith("--threshold="):
            threshold = float(a.split("=", 1)[1])
        else:
            paths.append(a)
    if len(paths) != 2:
        print("usage: python -m ray_tpu.bench_check OLD.json NEW.json "
              "[--threshold 0.10]", file=sys.stderr)
        return 2
    result = compare(load_metrics(paths[0]), load_metrics(paths[1]),
                     threshold=threshold)
    print(format_report(result, paths[0], paths[1], threshold))
    return 1 if result["regressions"] or result["missing"] else 0


if __name__ == "__main__":
    sys.exit(main())
