"""Overload bench: goodput under 2× offered load, protection ON vs OFF.

ISSUE 12 acceptance cells, runnable standalone (``python -m ray_tpu.cli
bench overload``) or inside ``bench.py``:

  * ``serve_goodput_frac`` — completed-within-deadline / offered at 2×
    the measured capacity THROUGH the real stack (HTTP proxy → router →
    replica → engine) with overload protection ON: request deadlines
    (``x-raytpu-deadline-ms``) + a bounded per-replica admission queue.
    Admitted work keeps a bounded TTFT; the rest fails fast and honest.
  * ``serve_goodput_frac_unprotected`` — the SAME storm against an app
    with no deadline and an unbounded queue: every request's TTFT blows
    up together (the congestion collapse this PR prevents). The
    acceptance bar is protection ON strictly above this baseline cell.
  * ``serve_shed_fast_fail_p95_ms`` — p95 time-to-503 of a shed request
    (bound ≤ 100 ms on the CPU sandbox: an honest rejection must be
    cheap).
  * ``serve_admitted_p95_ttft_ms`` — client TTFT p95 of ADMITTED
    requests under the protected storm.
  * ``serve_overload_parity`` — 1.0 iff every admitted re-issue of a
    reference prompt returns byte-identical greedy text.

CPU-sandbox friendly (debug preset engines); set
``RAY_TPU_BENCH_SKIP_OVERLOAD=1`` to leave ``*_skipped`` markers that
``bench_check`` honors.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

SKIP_MARKERS = {
    "serve_goodput_frac_skipped": True,
    "serve_shed_fast_fail_p95_ms_skipped": True,
    "serve_admitted_p95_ttft_ms_skipped": True,
}


def _pct(sorted_vals: list[float], q: float) -> float:
    return sorted_vals[max(0, int(len(sorted_vals) * q) - 1)]


def _one_request(addr: str, route: str, prompt: str, max_tokens: int,
                 deadline_ms: float | None, client_timeout: float) -> dict:
    """Drive one streaming completion; returns {"status", "ttft_s",
    "wall_s", "text", "finish", "retry_after"} — status is the HTTP code
    ("200"/"503"/"504") or an exception name (client-side timeout =
    abandoned, the open-loop client gave up)."""
    body = json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                       "stream": True}).encode()
    headers = {"Content-Type": "application/json"}
    if deadline_ms:
        headers["x-raytpu-deadline-ms"] = str(int(deadline_ms))
    req = urllib.request.Request(addr + route + "/v1/completions",
                                 data=body, headers=headers)
    t0 = time.perf_counter()
    out = {"status": "200", "ttft_s": None, "wall_s": None, "text": "",
           "finish": "", "retry_after": None}
    try:
        with urllib.request.urlopen(req, timeout=client_timeout) as resp:
            for line in resp:
                line = line.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                choice = json.loads(line[6:])["choices"][0]
                if out["ttft_s"] is None and choice.get("text"):
                    # Only a real token counts as the first token: the
                    # terminal deadline event carries no text.
                    out["ttft_s"] = time.perf_counter() - t0
                out["text"] += choice.get("text", "")
                if choice.get("finish_reason"):
                    out["finish"] = choice["finish_reason"]
    except urllib.error.HTTPError as e:
        out["status"] = str(e.code)
        out["retry_after"] = e.headers.get("Retry-After")
        try:
            e.read()
        except Exception:
            pass
    except Exception as e:
        out["status"] = type(e).__name__
    out["wall_s"] = time.perf_counter() - t0
    return out


def _storm(addr: str, route: str, schedule: list[tuple[float, str]],
           max_tokens: int, deadline_ms: float | None,
           client_timeout: float) -> list[dict]:
    """Fire the deterministic open-loop arrival schedule: each request
    launches at its offset regardless of how the previous ones fare —
    offered load is independent of service rate (the thundering herd)."""
    results: list[dict | None] = [None] * len(schedule)
    t0 = time.perf_counter()

    def fire(i: int, offset: float, prompt: str) -> None:
        delay = t0 + offset - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        results[i] = _one_request(addr, route, prompt, max_tokens,
                                  deadline_ms, client_timeout)

    threads = [threading.Thread(target=fire, args=(i, off, p), daemon=True)
               for i, (off, p) in enumerate(schedule)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=client_timeout + 60)
    return [r or {"status": "Unjoined", "wall_s": None, "ttft_s": None,
                  "text": "", "finish": ""} for r in results]


def run_overload_bench(storm_s: float | None = None,
                       deadline_ms: float | None = None) -> dict:
    if os.environ.get("RAY_TPU_BENCH_SKIP_OVERLOAD") == "1":
        return dict(SKIP_MARKERS)
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    preset = os.environ.get("RAY_TPU_OVERLOAD_PRESET", "debug-128")
    storm_s = storm_s or float(os.environ.get("RAY_TPU_OVERLOAD_STORM_S", "8"))
    deadline_ms = deadline_ms or float(
        os.environ.get("RAY_TPU_OVERLOAD_DEADLINE_MS", "2500"))
    calib_s = float(os.environ.get("RAY_TPU_OVERLOAD_CALIB_S", "4"))
    max_tokens = 8
    max_slots = 4

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    common = dict(max_slots=max_slots, max_len=256, page_size=16,
                  prefill_chunk_size=64, num_replicas=2,
                  max_ongoing_requests=64)
    # Protection ON: bounded per-replica admission queue (+ the deadline
    # each storm request carries). OFF: unbounded queue, no deadline —
    # the classic collapse baseline.
    serve.run(build_llm_app(preset, max_queued_requests=max_slots, **common),
              name="ovl-on", route_prefix="/on", timeout_s=360.0)
    serve.run(build_llm_app(preset, max_queued_requests=0, **common),
              name="ovl-off", route_prefix="/off", timeout_s=360.0)
    addr = serve.http_address()
    out: dict = {}
    try:
        def prompt_for(tag: str, i: int) -> str:
            return f"req {tag}-{i}: " + "abcdefgh" * (8 + i % 7)

        # Warm BOTH apps with every storm prompt SHAPE (all 7 length
        # variants hit every prefill bucket), concurrently enough that
        # both replicas of each pool compile — the storm and the
        # baseline cell must measure queueing, not first-touch XLA.
        for route in ("/on", "/off"):
            warm = [threading.Thread(
                target=_one_request,
                args=(addr, route, prompt_for("warm", i), max_tokens,
                      None, 180.0), daemon=True) for i in range(14)]
            for t in warm:
                t.start()
            for t in warm:
                t.join(timeout=240)

        # ---- capacity calibration: closed-loop at ~2x slot concurrency
        # against the protected app (post-warm, so it measures service
        # rate, not compiles).
        done = {"n": 0}
        lock = threading.Lock()
        stop_at = time.perf_counter() + calib_s

        def calib_client(cid: int) -> None:
            j = 0
            while time.perf_counter() < stop_at:
                r = _one_request(addr, "/on", prompt_for(f"c{cid}", j),
                                 max_tokens, None, 120.0)
                j += 1
                if r["status"] == "200":
                    with lock:
                        done["n"] += 1

        cthreads = [threading.Thread(target=calib_client, args=(i,),
                                     daemon=True)
                    for i in range(4 * max_slots)]
        t0 = time.perf_counter()
        for t in cthreads:
            t.start()
        for t in cthreads:
            t.join(timeout=calib_s + 120)
        capacity_rps = done["n"] / max(1e-3, time.perf_counter() - t0)
        if capacity_rps <= 0:
            raise RuntimeError("capacity calibration served 0 requests")
        offered_rps = 2.0 * capacity_rps
        # Cap the herd so the baseline cell can't run away on a fast box
        # (offered load, not thread count, is the variable under test).
        n_offered = min(160, max(16, int(offered_rps * storm_s)))

        # ---- parity references: unique prompts served UNLOADED; their
        # storm re-issues must return byte-identical greedy text.
        ref_prompts = [prompt_for("ref", i) for i in range(4)]
        references = {}
        for p in ref_prompts:
            r = _one_request(addr, "/on", p, max_tokens, None, 120.0)
            if r["status"] == "200":
                references[p] = r["text"]

        # Deterministic thundering-herd schedule: evenly spaced arrivals
        # at 2× capacity; every 8th request re-issues a reference prompt.
        def schedule_for(tag: str) -> list[tuple[float, str]]:
            sched = []
            for i in range(n_offered):
                if i % 8 == 0 and ref_prompts:
                    p = ref_prompts[(i // 8) % len(ref_prompts)]
                else:
                    p = prompt_for(tag, i)
                sched.append((i / offered_rps, p))
            return sched

        budget_s = deadline_ms / 1000.0
        client_timeout = budget_s * 4 + 10.0

        # ---- protection ON storm (deadline header + bounded queues).
        on = _storm(addr, "/on", schedule_for("on"), max_tokens,
                    deadline_ms, client_timeout)
        # ---- protection OFF baseline cell (same offered load, no
        # protection): goodput judged against the SAME budget.
        off = _storm(addr, "/off", schedule_for("off"), max_tokens,
                     None, client_timeout)

        def goodput(results: list[dict]) -> float:
            ok = sum(1 for r in results
                     if r["status"] == "200" and r["wall_s"] is not None
                     and r["wall_s"] <= budget_s
                     and r["finish"] not in ("deadline", "timeout"))
            return ok / max(1, len(results))

        sheds = [r for r in on if r["status"] == "503"]
        expired = [r for r in on if r["status"] == "504"
                   or r["finish"] == "deadline"]
        admitted_ttfts = sorted(
            r["ttft_s"] for r in on
            if r["status"] == "200" and r["ttft_s"] is not None)
        parity = 1.0
        for results in (on,):
            for (off_t, p), r in zip(schedule_for("on"), results):
                if p in references and r["status"] == "200" \
                        and r["finish"] not in ("deadline", "timeout") \
                        and r["text"] != references[p]:
                    parity = 0.0
        out["serve_goodput_frac"] = round(goodput(on), 4)
        out["serve_goodput_frac_unprotected"] = round(goodput(off), 4)
        out["serve_overload_offered"] = n_offered
        out["serve_overload_completed"] = sum(
            1 for r in on if r["status"] == "200")
        out["serve_shed_requests"] = len(sheds)
        out["serve_deadline_expired"] = len(expired)
        out["serve_capacity_rps_cfg"] = round(capacity_rps, 2)
        out["serve_overload_parity"] = parity if references else None
        if sheds:
            fails = sorted(r["wall_s"] for r in sheds)
            out["serve_shed_fast_fail_p95_ms"] = round(
                1000 * _pct(fails, 0.95), 1)
        else:
            out["serve_shed_fast_fail_p95_ms_skipped"] = True
        if admitted_ttfts:
            out["serve_admitted_p95_ttft_ms"] = round(
                1000 * _pct(admitted_ttfts, 0.95), 1)
        else:
            out["serve_admitted_p95_ttft_ms_skipped"] = True
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
    return out


if __name__ == "__main__":
    print(json.dumps(run_overload_bench()))
