"""Serve controller: reconciles deployment target state onto replica actors.

Reference: ``python/ray/serve/_private/controller.py:84`` (ServeController)
+ ``deployment_state.py:1249`` (replica FSM / rolling updates) +
``autoscaling_state.py`` (queue-based autoscaling). One detached named
actor owns all Serve state: a reconcile thread diffs target vs running
replicas, starts/drains replica actors, health-checks them, and pushes
routing tables to routers via the long-poll host. State is checkpointed
to the GCS KV after every mutation so a restarted controller can
re-adopt running replicas.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any

import cloudpickle

from ..core import api as ray
from ..chaos import clock as chaos_clock
from . import fleet as fleet_policy
from .long_poll import LongPollHost

logger = logging.getLogger(__name__)

# Replica FSM states (reference deployment_state.py ReplicaState).
STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"
# Always-warm fleet (serve/fleet.py): replica alive with weights in host
# RAM and the compile cache warm — excluded from routing (the table only
# carries RUNNING), promoted back via one fleet_promote RPC.
STANDBY = "STANDBY"

CHECKPOINT_KEY = "serve:controller:checkpoint"


class _Replica:
    def __init__(self, replica_id: str, version: str, actor_handle, actor_id: bytes):
        self.replica_id = replica_id
        self.version = version
        self.actor = actor_handle
        self.actor_id = actor_id
        self.state = STARTING
        self.ready_ref = None
        self.started_at = time.time()
        self.health_failures = 0
        self.draining_since = 0.0
        self.applied_user_config = None
        # GCS-resolved placement, filled lazily by the probe phase: the
        # preemption-eviction path needs replica -> node without an RPC
        # to the (possibly dying) replica itself.
        self.node_id = ""
        # Last latency/residency probe (monotonic): outside latency_slo
        # mode the snapshot is pulled at a relaxed cadence — residency
        # doesn't need the every-round freshness autoscaling does.
        self.last_latency_probe = 0.0
        # Set when a fleet_demote reported "unsupported" (plain callable
        # or sharded executor): the replica stays RUNNING and the
        # standby machinery stops retrying it.
        self.fleet_unsupported = False


class _DeploymentState:
    def __init__(self, app_name: str, config: dict):
        self.app_name = app_name
        self.config = config  # name, serialized_callable, init args, options
        self.version = config["version"]
        self.replicas: list[_Replica] = []
        self.next_replica_no = 0
        self.autoscale_history: list[tuple[float, float]] = []
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        # latency_slo mode: ring of (ts, {metric: (buckets, boundaries,
        # count)}) cumulative snapshots for windowed quantiles, breach/
        # clear streak counters (hysteresis), and the decision history
        # surfaced in `cli serve status` / get_app_status.
        self.latency_history: list[tuple[float, dict]] = []
        self.slo_breach_streak = 0
        self.slo_ok_streak = 0
        self.scale_events: list[dict] = []
        self.target_replicas = config["num_replicas"]
        # crash-loop backoff: consecutive failed starts delay the next one
        # exponentially (a broken constructor must not spin replica churn)
        self.consecutive_start_failures = 0
        self.next_start_allowed = 0.0
        # The most recent replica-start failure's exception text — surfaced
        # in the controller log, get_app_status(), and the error-info
        # channel so "failed to start" is never cause-less.
        self.last_start_failure: str | None = None
        # Proactive preemption evictions (resilience): one row per replica
        # removed because its NODE got a preemption notice — `reroute_s`
        # (notice -> eviction+table push, chaos-clock) is the serve half
        # of the recovery SLO bench.
        self.preemption_evictions: list[dict] = []
        # Aggregated prefix-group residency from the replicas' probe
        # rows (affinity hit rates in status; empty = no LLM engines).
        self.prefix_affinity: dict = {}
        # Aggregated overload counters from the replicas' probe rows
        # (deadline expiries, engine-queue sheds, admission rejects).
        self.overload: dict = {}
        # Aggregated per-tenant state from the replicas' ``serve_tenancy``
        # probe rows (quota counters, windowed TTFT p95, resident
        # adapters) — surfaced in status and fed to the latency-SLO
        # autoscaler so one noisy tenant's breach triggers scaling.
        self.tenancy: dict = {}
        # Always-warm fleet: folded ``serve_fleet`` probe rows (fleet
        # idle age + weight residency), the scale-to-zero latch, the
        # router-signalled first-request wake, the last standby
        # promotion (timing surfaces in status / `cli serve status`),
        # and the TTFT trend samples predictive upscale extrapolates.
        self.fleet: dict = {}
        self.scaled_to_zero = False
        self.wake_pending = False
        self.last_promote: dict | None = None
        self.ttft_trend: list[tuple[float, float]] = []
        # Wall time of the last wake/scheduled un-zero: replicas keep
        # reporting their pre-wake idle age until the first request
        # lands, so scale-to-zero holds off for a grace window after a
        # wake or the pool would re-latch before serving anything.
        self.last_wake = 0.0

    @property
    def name(self) -> str:
        return self.config["name"]


class ServeController:
    """The detached SERVE_CONTROLLER actor."""

    def __init__(self):
        self._lock = threading.RLock()
        self._apps: dict[str, dict[str, _DeploymentState]] = {}
        self._routes: dict[str, tuple[str, str]] = {}  # prefix -> (app, ingress dep)
        self._long_poll = LongPollHost()
        self._stopped = threading.Event()
        # node_id -> PreemptionNotice for draining/preempted nodes
        # (resilience/preemption.py), refreshed by the reconcile loop.
        self._hazard_nodes: dict = {}
        self._hazard_refreshed = 0.0
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._recover()
        self._reconcile_thread.start()

    # ------------------------------------------------------------ public API
    def deploy_application(self, app_name: str, route_prefix: str | None,
                           deployments: list[dict], ingress: str) -> bool:
        """Set/replace target state for an application (reference
        controller.deploy_application)."""
        with self._lock:
            existing = self._apps.get(app_name, {})
            new_states: dict[str, _DeploymentState] = {}
            for config in deployments:
                name = config["name"]
                state = existing.get(name)
                if state is None:
                    state = _DeploymentState(app_name, config)
                else:
                    state.config = config
                    if state.version != config["version"]:
                        state.version = config["version"]  # reconcile rolls replicas
                    auto = config.get("autoscaling")
                    if auto:
                        # keep the autoscaler's current target, clamped to
                        # the new bounds
                        state.target_replicas = max(
                            auto["min_replicas"],
                            min(auto["max_replicas"], state.target_replicas),
                        )
                    else:
                        state.target_replicas = config["num_replicas"]
                new_states[name] = state
            # deployments removed from the app drain in reconcile
            for name, state in existing.items():
                if name not in new_states:
                    state.target_replicas = 0
                    state.config["deleted"] = True
                    new_states[name] = state
            self._apps[app_name] = new_states
            if route_prefix is not None:
                self._routes = {p: t for p, t in self._routes.items() if t[0] != app_name}
                self._routes[route_prefix] = (app_name, ingress)
            self._push_routes()
            for state in new_states.values():
                self._push_tenancy(state)
            self._checkpoint()
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return False
            for state in app.values():
                state.target_replicas = 0
                state.config["deleted"] = True
            self._routes = {p: t for p, t in self._routes.items() if t[0] != app_name}
            self._push_routes()
            self._checkpoint()
        return True

    def get_app_status(self, app_name: str) -> dict:
        with self._lock:
            app = self._apps.get(app_name, {})
            out = {}
            for name, state in app.items():
                running = [r for r in state.replicas if r.state == RUNNING and r.version == state.version]
                standby = [r for r in state.replicas
                           if r.state == STANDBY and r.version == state.version]
                auto = state.config.get("autoscaling") or {}
                out[name] = {
                    "target_replicas": state.target_replicas,
                    "running_replicas": len(running),
                    "standby_replicas": len(standby),
                    "version": state.version,
                    # Disaggregated pool membership ("prefill"/"decode",
                    # None for unified deployments).
                    "pool": state.config.get("pool"),
                    # A deployment parked at zero with a warm standby
                    # pool is healthy by design, not degraded.
                    "healthy": (len(running) >= state.target_replicas
                                or (state.scaled_to_zero and bool(standby))),
                    "scaled_to_zero": state.scaled_to_zero,
                    "fleet": dict(state.fleet),
                    "last_promote": (dict(state.last_promote)
                                     if state.last_promote else None),
                    "deleted": bool(state.config.get("deleted")),
                    "last_start_failure": state.last_start_failure,
                    "autoscaling_mode": auto.get("mode") if auto else None,
                    "autoscale_events": list(state.scale_events[-10:]),
                    "preemption_evictions": list(state.preemption_evictions[-10:]),
                    "prefix_affinity": dict(state.prefix_affinity),
                    "overload": dict(state.overload),
                    "tenancy": dict(state.tenancy),
                }
            return out

    def wake_deployment(self, app_name: str, name: str | None = None) -> bool:
        """First-request wake: routers call this (fire-and-forget) when a
        request lands on an empty replica table. The next reconcile
        round clears scale-to-zero and promotes standbys."""
        woke = False
        with self._lock:
            for dname, state in (self._apps.get(app_name) or {}).items():
                if name is not None and dname != name:
                    continue
                state.wake_pending = True
                woke = True
        return woke

    def update_tenancy_config(self, app_name: str, name: str | None,
                              tenancy_config: dict) -> dict:
        """Live tenant reconfigure: swap a deployment's tenancy config
        (WFQ weights / quotas) and re-publish the folded weights on the
        ``tenancy::`` long-poll key — routers pick the new shares up
        mid-run, no redeploy, no replica restart."""
        updated = []
        with self._lock:
            for dname, state in (self._apps.get(app_name) or {}).items():
                if name is not None and dname != name:
                    continue
                kwargs = dict(state.config.get("init_kwargs") or {})
                kwargs["tenancy_config"] = tenancy_config
                state.config["init_kwargs"] = kwargs
                self._push_tenancy(state)
                updated.append(dname)
        if updated:
            self._checkpoint()
        return {"updated": updated}

    def list_deployments(self) -> dict:
        with self._lock:
            return {
                app: {name: s.config["name"] for name, s in deps.items()}
                for app, deps in self._apps.items()
            }

    def get_ingress(self, route_prefix: str) -> tuple[str, str] | None:
        with self._lock:
            return self._routes.get(route_prefix)

    def listen_for_change(self, keys_to_snapshot_ids: dict) -> dict:
        return self._long_poll.listen_for_change(keys_to_snapshot_ids)

    def get_snapshot(self, key: str):
        return self._long_poll.get(key)[1]

    def register_proxy(self, actor_id: bytes) -> bool:
        # push the current routing table to the newly-attached proxy
        self._push_routes()
        return True

    def graceful_shutdown(self) -> bool:
        """Drain every replica before the controller itself is killed."""
        with self._lock:
            for app in self._apps.values():
                for state in app.values():
                    state.target_replicas = 0
                    state.config["deleted"] = True
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with self._lock:
                if all(not s.replicas for app in self._apps.values() for s in app.values()):
                    break
            time.sleep(0.1)
        self._stopped.set()
        try:
            ray.global_worker()._gcs_call("KvDel", {"key": CHECKPOINT_KEY})
        except Exception:
            pass
        return True

    # ------------------------------------------------------- reconciliation
    def _reconcile_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("serve reconcile iteration failed")
            self._stopped.wait(0.25)

    def _refresh_hazard_nodes(self) -> None:
        """Poll the preemption signals (GCS node table ``draining`` flags
        + ``node_preempted`` ErrorEvents) at most twice a second. This is
        what makes replica eviction PROACTIVE: the router stops getting a
        doomed replica at the NOTICE, not after per-request deaths or
        three failed 10 s health probes."""
        now = time.monotonic()
        if now - self._hazard_refreshed < 0.5:
            return
        self._hazard_refreshed = now
        from ..resilience.preemption import hazard_nodes

        self._hazard_nodes = hazard_nodes(
            lambda method, payload: ray.global_worker()._gcs_call(method, payload))

    def _reconcile_once(self) -> None:
        self._refresh_hazard_nodes()
        with self._lock:
            apps = {a: dict(deps) for a, deps in self._apps.items()}
        dirty = False
        for app_name, deps in apps.items():
            for state in deps.values():
                dirty |= self._reconcile_deployment(state)
        with self._lock:
            # drop fully-drained deleted deployments
            for app_name in list(self._apps):
                deps = self._apps[app_name]
                for name in list(deps):
                    s = deps[name]
                    if s.config.get("deleted") and not s.replicas:
                        del deps[name]
                        dirty = True
                if not deps:
                    del self._apps[app_name]
        if dirty:
            self._checkpoint()

    def _reconcile_deployment(self, state: _DeploymentState) -> bool:
        # ---- probe phase: all blocking replica RPCs happen WITHOUT the
        # controller lock, so a hung replica can't freeze the control plane.
        with self._lock:
            replicas = list(state.replicas)
            user_config = state.config.get("user_config")
        probes: dict[str, dict] = {}
        for r in replicas:
            p: dict = {}
            if r.state == STARTING:
                if r.ready_ref is None:
                    r.ready_ref = r.actor.ready.remote()
                try:
                    done, _ = ray.wait([r.ready_ref], num_returns=1, timeout=0)
                    if done:
                        ray.get(done[0], timeout=5)
                        p["ready"] = True
                except Exception as e:
                    p["failed"] = True
                    # Keep the replica's ACTUAL exception (an ActorDiedError
                    # here embeds the creation task's traceback): the
                    # "failed to start" log line must name the cause.
                    p["failure"] = f"{type(e).__name__}: {e}"
            elif r.state in (RUNNING, STANDBY):
                # STANDBY replicas ride the same probe path: liveness,
                # reconfigure, and the fleet/latency snapshot all still
                # apply — only routing excludes them.
                if not r.node_id:
                    # Resolve placement from the GCS actor table (never
                    # from the replica: a preempted node may not answer).
                    try:
                        info = ray.global_worker()._gcs_call(
                            "GetActorInfo", {"actor_id": r.actor_id.hex()})
                        r.node_id = info.get("node_id") or ""
                    except Exception:
                        pass
                p["alive"] = self._replica_alive(r)
                try:
                    p["queue"] = ray.get(r.actor.get_queue_len.remote(), timeout=5)
                except Exception:
                    p["queue"] = 0
                # Probed in every mode (not only latency_slo): the same
                # snapshot carries the serve_prefix_residency row that
                # feeds the affinity hit rates in app status — but
                # outside slo mode only every ~2 s, not every round.
                auto = state.config.get("autoscaling") or {}
                now_m = time.monotonic()
                want_latency = (auto.get("mode") == "latency_slo"
                                or now_m - r.last_latency_probe >= 2.0)
                if p["alive"] and want_latency:
                    r.last_latency_probe = now_m
                    try:
                        p["latency"] = ray.get(
                            r.actor.latency_snapshot.remote(), timeout=5)
                    except Exception:
                        p["latency"] = []
                if p["alive"] and r.applied_user_config != user_config:
                    # config-only change: in-place reconfigure, no restart
                    try:
                        ray.get(r.actor.reconfigure.remote(user_config), timeout=30)
                        r.applied_user_config = user_config
                    except Exception:
                        logger.warning("reconfigure of %s failed", r.replica_id)
            elif r.state == STOPPING:
                try:
                    p["queue"] = ray.get(r.actor.get_queue_len.remote(), timeout=5)
                except Exception:
                    p["queue"] = 0
            probes[r.replica_id] = p

        # ---- decision phase: mutate under the lock, RPC-free.
        to_kill: list[_Replica] = []
        to_promote: list[_Replica] = []
        to_demote: list[_Replica] = []
        n_to_start = 0
        dirty = False
        with self._lock:
            self._fold_prefix_residency(state, probes)
            self._fold_overload(state, probes)
            self._fold_tenancy(state, probes)
            # Re-publish tenancy when the folded retire-time cost
            # correction moved, so routers scale their WFQ estimates.
            corr = {t: row.get("cost_correction")
                    for t, row in ((state.tenancy or {}).get("tenants")
                                   or {}).items()
                    if row.get("cost_correction") is not None}
            if not hasattr(self, "_pushed_corrections"):
                self._pushed_corrections = {}
            ckey = f"{state.app_name}::{state.name}"
            if corr and corr != self._pushed_corrections.get(ckey):
                self._pushed_corrections[ckey] = corr
                self._push_tenancy(state)
            self._fold_fleet(state, probes)
            self._autoscale_from_probes(state, probes)
            self._apply_fleet_policy(state)
            target = state.target_replicas
            for r in list(state.replicas):
                p = probes.get(r.replica_id, {})
                if r.state == STARTING:
                    if p.get("ready"):
                        r.state = RUNNING
                        # Keep the CONSTRUCTION-time user_config recorded at
                        # _start_replica: if the target config changed while
                        # this replica was starting, the next probe's
                        # reconfigure pass must still see the mismatch and
                        # apply it (overwriting with the probe-time config
                        # here silently skipped the update).
                        state.consecutive_start_failures = 0
                        state.next_start_allowed = 0.0
                        state.last_start_failure = None
                        dirty = True
                    elif p.get("failed"):
                        cause = p.get("failure") or "unknown cause"
                        state.consecutive_start_failures += 1
                        state.last_start_failure = cause
                        delay = min(30.0, 0.5 * 2 ** min(state.consecutive_start_failures, 6))
                        # Chaos clock: restart backoff replays deterministically
                        # under time=virtual (chaos/clock.py).
                        state.next_start_allowed = chaos_clock.now() + delay
                        logger.warning(
                            "replica %s failed to start; replacing in %.1fs "
                            "(%d consecutive failures): %s",
                            r.replica_id, delay,
                            state.consecutive_start_failures, cause)
                        from ..diagnostics.errors import publish_error_to_driver

                        publish_error_to_driver(
                            "replica_start_failure",
                            f"replica {r.replica_id} failed to start: "
                            + cause.splitlines()[0],
                            source="serve_controller", traceback=cause,
                            extra={"app": state.app_name,
                                   "deployment": state.name,
                                   "replica_id": r.replica_id})
                        state.replicas.remove(r)
                        to_kill.append(r)
                        dirty = True
                elif r.state == RUNNING and r.node_id in self._hazard_nodes:
                    # Proactive preemption eviction: the replica's NODE
                    # got a preemption notice — stop routing to it NOW,
                    # while it is still technically alive, instead of
                    # waiting for per-request ActorDiedErrors after the
                    # grace-window kill.
                    notice = self._hazard_nodes[r.node_id]
                    now_c = chaos_clock.now()
                    event = {
                        "replica_id": r.replica_id,
                        "node_id": r.node_id,
                        "reason": getattr(notice, "reason", ""),
                        "notice_clock": getattr(notice, "notice_clock", now_c),
                        "evicted_clock": now_c,
                    }
                    event["reroute_s"] = round(
                        max(0.0, now_c - event["notice_clock"]), 4)
                    state.preemption_evictions.append(event)
                    del state.preemption_evictions[:-20]
                    logger.warning(
                        "replica %s evicted: node %s preempted (reroute "
                        "%.2fs after the notice)", r.replica_id,
                        r.node_id[:8], event["reroute_s"])
                    # Drain, don't kill: routing stops immediately (the
                    # table only carries RUNNING replicas) while requests
                    # already on the replica finish inside the grace
                    # window. The STOPPING cleanup reaps it.
                    self._drain_replica(r)
                    dirty = True
                elif r.state in (RUNNING, STANDBY) and not p.get("alive", True):
                    logger.warning("replica %s died; removing", r.replica_id)
                    state.replicas.remove(r)
                    to_kill.append(r)
                    dirty = True
                elif r.state == STOPPING and (
                    p.get("queue", 0) == 0
                    or chaos_clock.now() - r.draining_since > 15.0
                ):
                    state.replicas.remove(r)
                    to_kill.append(r)
                    dirty = True
            current = [r for r in state.replicas if r.state in (STARTING, RUNNING)]
            cur_version = [r for r in current if r.version == state.version]
            old_version = [r for r in current if r.version != state.version]
            # Standby replicas of a superseded version (or of a deleted
            # deployment) carry stale weights — drain them; the warm pool
            # only ever serves the current version.
            for r in list(state.replicas):
                if r.state == STANDBY and (
                        r.version != state.version
                        or state.config.get("deleted")):
                    self._drain_replica(r)
                    dirty = True
            # rolling update: surge one new replica, then drain one old
            # (deployment_state.py rolling update with max surge 1)
            if old_version:
                if len(cur_version) < target + 1 and not any(r.state == STARTING for r in cur_version):
                    n_to_start = 1
                if any(r.state == RUNNING for r in cur_version):
                    self._drain_replica(old_version[0])
                    dirty = True
            else:
                auto = state.config.get("autoscaling")
                # Standby pool size only applies to fleet-capable
                # deployments (ones whose replicas report serve_fleet
                # rows) — a plain-callable deployment never demotes.
                # A deleted deployment must never refill its pool: the
                # stale-standby drain above empties it, and a nonzero
                # want_standby here would restart a replica every round
                # until the shutdown deadline (start→demote→drain storm).
                want_standby = (fleet_policy.desired_standby(auto)
                                if state.fleet
                                and not state.config.get("deleted") else 0)
                standby = [r for r in state.replicas
                           if r.state == STANDBY
                           and r.version == state.version]
                eff_target = 0 if state.scaled_to_zero else target
                deficit = eff_target - len(cur_version)
                if deficit > 0:
                    # Promote warm standbys before starting cold
                    # replicas: promotion is one host→device transfer on
                    # a warm compile cache, a start is a full init.
                    to_promote = standby[:deficit]
                    n_to_start = deficit - len(to_promote)
                elif deficit < 0:
                    running = [r for r in cur_version if r.state == RUNNING]
                    excess = -deficit
                    for r in (running or cur_version)[:excess]:
                        if (r.state == RUNNING and not r.fleet_unsupported
                                and len(standby) + len(to_demote)
                                < want_standby):
                            to_demote.append(r)
                        else:
                            self._drain_replica(r)
                            dirty = True
                # Standby pool maintenance: with the active set
                # satisfied, grow the pool one replica per round — the
                # extra start turns RUNNING, becomes excess next round,
                # and the branch above demotes it into the pool.
                if (deficit <= 0 and not to_demote
                        and len(standby) < want_standby
                        and n_to_start == 0
                        and not any(r.state == STARTING for r in cur_version)):
                    n_to_start = 1

        # ---- action phase: actor create/kill RPCs without the lock.
        for r in to_kill:
            try:
                ray.kill(r.actor)
            except Exception:
                pass
        if n_to_start and chaos_clock.now() < state.next_start_allowed:
            n_to_start = 0  # crash-loop backoff window
        for _ in range(n_to_start):
            self._start_replica(state)
            dirty = True
        # Fleet transitions are replica RPCs, so they stay out of the
        # lock too. Demotion parks weights in host RAM; promotion walks
        # the replica's ladder (broadcast stream → host copy → cold
        # re-init) so a dead donor never strands a standby.
        for r in to_demote:
            try:
                res = ray.get(r.actor.fleet_demote.remote(), timeout=30) or {}
            except Exception as e:
                res = {"ok": False, "reason": f"rpc_failed: {e}"}
            if res.get("ok"):
                with self._lock:
                    r.state = STANDBY
                logger.info("replica %s demoted to standby (%s bytes to host)",
                            r.replica_id, res.get("bytes"))
                dirty = True
            elif res.get("reason") == "unsupported":
                r.fleet_unsupported = True
            # "busy": leave RUNNING; retried next round once drained.
        if to_promote:
            addr = self._weight_donor_address(state, to_promote)
            for r in to_promote:
                try:
                    res = ray.get(r.actor.fleet_promote.remote(addr),
                                  timeout=120) or {}
                except Exception as e:
                    res = {"ok": False, "path": f"rpc_failed: {e}"}
                if res.get("ok"):
                    with self._lock:
                        r.state = RUNNING
                        state.last_promote = {
                            "replica_id": r.replica_id,
                            "path": res.get("path"),
                            "seconds": res.get("seconds"),
                            "ts": time.time(),
                        }
                    logger.info("replica %s promoted via %s in %.3fs",
                                r.replica_id, res.get("path"),
                                float(res.get("seconds") or 0.0))
                else:
                    logger.warning("promotion of %s failed (%s); draining",
                                   r.replica_id, res.get("path"))
                    with self._lock:
                        self._drain_replica(r)
                dirty = True
        if dirty:
            with self._lock:
                self._push_replica_table(state)
        return dirty

    def _weight_donor_address(self, state: _DeploymentState,
                              to_promote: list) -> str | None:
        """For a fan-out promotion, open ONE weight broadcast on a donor
        replica so N cold promotions stream from a single reader-backed
        source instead of N separate loads. A single promotion uses its
        own host copy (the 'host' ladder rung) — no wire needed."""
        if len(to_promote) < 2:
            return None
        promoting = {r.replica_id for r in to_promote}
        with self._lock:
            donors = [r for r in state.replicas
                      if r.state in (RUNNING, STANDBY)
                      and r.replica_id not in promoting
                      and not r.fleet_unsupported]
        for donor in donors:
            try:
                res = ray.get(
                    donor.actor.open_weight_stream.remote(len(to_promote)),
                    timeout=30)
            except Exception:
                continue
            if res and res.get("weight_address"):
                return res["weight_address"]
        return None

    def _fold_fleet(self, state: _DeploymentState, probes: dict) -> None:
        """Fold the replicas' ``serve_fleet`` probe rows (request-idle
        age, weight residency) into the deployment view the fleet policy
        consumes. Held under the controller lock by the decision phase."""
        rows = []
        for p in probes.values():
            for row in p.get("latency") or []:
                if row.get("name") == "serve_fleet":
                    rows.append(row)
        folded = fleet_policy.fold_fleet_rows(rows)
        if folded is not None:
            state.fleet = folded

    def _apply_fleet_policy(self, state: _DeploymentState) -> None:
        """Scheduled capacity, wake, and scale-to-zero — the pure
        policy lives in serve/fleet.py; this applies its answers to the
        deployment FSM (called under the controller lock)."""
        auto = state.config.get("autoscaling")
        if not auto or state.config.get("deleted"):
            return
        now = time.time()
        floor = fleet_policy.scheduled_floor(
            auto.get("scheduled_capacity"), now)
        if floor > 0:
            floor = min(floor, int(auto.get("max_replicas") or floor))
            if state.scaled_to_zero:
                state.scaled_to_zero = False
                self._record_scale_event(
                    state, 0, state.target_replicas, "scheduled_capacity",
                    floor, floor)
            if state.target_replicas < floor:
                self._record_scale_event(
                    state, state.target_replicas, floor,
                    "scheduled_capacity", floor, floor)
                state.target_replicas = floor
        if floor > 0:
            state.last_wake = now
        if state.wake_pending:
            state.wake_pending = False
            if state.scaled_to_zero:
                # First request after scale-to-zero: the router saw an
                # empty replica table and poked us — promote NOW, don't
                # wait for an idle-age flip.
                state.scaled_to_zero = False
                state.last_wake = now
                self._record_scale_event(
                    state, 0, state.target_replicas, "wake", None,
                    state.target_replicas)
        idle_thresh = float(fleet_policy._cfg_get(
            auto, "scale_to_zero_idle_s", 0) or 0)
        woke_recently = (idle_thresh > 0
                         and now - state.last_wake < idle_thresh)
        if (not state.scaled_to_zero and floor == 0 and not woke_recently
                and fleet_policy.should_scale_to_zero(
                    (state.fleet or {}).get("idle_s"), auto)
                and state.fleet.get("residency_capable")):
            state.scaled_to_zero = True
            self._record_scale_event(
                state, state.target_replicas, 0, "scale_to_zero",
                state.fleet.get("idle_s"),
                fleet_policy._cfg_get(auto, "scale_to_zero_idle_s"))

    @staticmethod
    def _fold_prefix_residency(state: _DeploymentState, probes: dict) -> None:
        """Sum the replicas' ``serve_prefix_residency`` probe rows into
        the deployment's affinity view: resident groups, requests, and
        the replica-local prefix-cache hit rate (how often an affine
        request found its KV where the router sent it)."""
        agg = {"replicas": 0, "groups": 0, "requests": 0, "cache_hits": 0}
        for p in probes.values():
            for row in p.get("latency") or []:
                if row.get("name") != "serve_prefix_residency":
                    continue
                agg["replicas"] += 1
                for k in ("groups", "requests", "cache_hits"):
                    agg[k] += int(row.get(k, 0) or 0)
        if agg["replicas"]:
            agg["hit_rate"] = (round(agg["cache_hits"] / agg["requests"], 4)
                               if agg["requests"] else 0.0)
            state.prefix_affinity = agg

    @staticmethod
    def _fold_overload(state: _DeploymentState, probes: dict) -> None:
        """Sum the replicas' ``serve_overload`` probe rows (engine-side
        deadline expiries, queue sheds, admission-watermark rejects)
        into the deployment's overload view for ``serve.status()``."""
        keys = ("deadline_expired_queued", "deadline_expired_running",
                "queue_rejects", "admission_rejects")
        agg = {k: 0 for k in keys}
        replicas = 0
        for p in probes.values():
            for row in p.get("latency") or []:
                if row.get("name") != "serve_overload":
                    continue
                replicas += 1
                for k in keys:
                    agg[k] += int(row.get(k, 0) or 0)
        if replicas:
            agg["replicas"] = replicas
            state.overload = agg

    @staticmethod
    def _fold_tenancy(state: _DeploymentState, probes: dict) -> None:
        """Merge the replicas' ``serve_tenancy`` probe rows into one
        per-tenant view: counters sum across replicas, the windowed TTFT
        p95 takes the worst replica (one hot replica breaching the SLO
        is a breach), and each replica's resident adapters are unioned.
        Feeds ``serve.status()`` and the latency-SLO autoscaler."""
        sum_keys = ("admitted", "shed", "quota_rejects",
                    "tokens_in", "tokens_out")
        tenants: dict[str, dict] = {}
        resident: list[str] = []
        last_breaches: list[dict] = []
        adapter_defers = 0
        replicas = 0
        for p in probes.values():
            for row in p.get("latency") or []:
                if row.get("name") != "serve_tenancy":
                    continue
                replicas += 1
                adapter_defers += int(row.get("adapter_defers", 0) or 0)
                for aid in row.get("resident_adapters") or []:
                    if aid not in resident:
                        resident.append(aid)
                last_breaches.extend(row.get("last_breaches") or [])
                for tenant, t_row in (row.get("tenants") or {}).items():
                    agg = tenants.setdefault(
                        tenant, {k: 0 for k in sum_keys})
                    for k in sum_keys:
                        agg[k] += int(t_row.get(k, 0) or 0)
                    agg["weight"] = t_row.get("weight", agg.get("weight", 1.0))
                    p95 = t_row.get("p95_ttft_ms")
                    if p95 is not None:
                        agg["p95_ttft_ms"] = max(
                            float(p95), float(agg.get("p95_ttft_ms") or 0.0))
                    burn = t_row.get("slo_burn_frac")
                    if burn is not None:
                        # like p95: one hot replica burning the SLO IS a
                        # burn — take the worst replica's fraction
                        agg["slo_burn_frac"] = max(
                            float(burn), float(agg.get("slo_burn_frac")
                                               or 0.0))
                        agg["ttft_slo_ms"] = t_row.get(
                            "ttft_slo_ms", agg.get("ttft_slo_ms"))
                        agg["slo_breaches"] = int(agg.get("slo_breaches", 0)) \
                            + int(t_row.get("slo_breaches", 0) or 0)
                    corr = t_row.get("cost_correction")
                    if corr is not None:
                        # mean across reporting replicas (each is already
                        # an EWMA over that replica's retires)
                        n = int(agg.get("_corr_n", 0))
                        prev = float(agg.get("cost_correction") or 0.0)
                        agg["cost_correction"] = round(
                            (prev * n + float(corr)) / (n + 1), 4)
                        agg["_corr_n"] = n + 1
                    remaining = t_row.get("quota_remaining")
                    if remaining is not None:
                        # quota buckets are per-replica: remaining budget
                        # across the deployment is their sum
                        agg["quota_remaining"] = round(
                            float(agg.get("quota_remaining") or 0.0)
                            + float(remaining), 1)
        for agg in tenants.values():
            agg.pop("_corr_n", None)
        if replicas:
            # Most recent breach dumps across the fleet, newest last.
            last_breaches.sort(key=lambda b: b.get("ts", 0.0))
            state.tenancy = {
                "replicas": replicas,
                "tenants": tenants,
                "resident_adapters": resident,
                "adapter_defers": adapter_defers,
                "last_breaches": last_breaches[-8:],
                # Counters/quota sum over N per-replica ledgers: an
                # N-replica deployment admits ~N× a single replica's
                # tokens_per_s quota (each replica meters independently).
                "scope": "per_replica_sum",
            }

    def _replica_alive(self, r: _Replica) -> bool:
        try:
            ray.get(r.actor.check_health.remote(), timeout=10)
            r.health_failures = 0
            return True
        except Exception:
            r.health_failures += 1
            return r.health_failures < 3

    def _start_replica(self, state: _DeploymentState) -> None:
        from .replica import ReplicaActor

        with self._lock:
            cfg = state.config
            state.next_replica_no += 1
            replica_id = f"{state.app_name}#{state.name}#{state.next_replica_no}"
            version = state.version
        actor_options = dict(cfg.get("ray_actor_options") or {})
        actor_options.setdefault("num_cpus", 0.1)
        cls = ray.remote(ReplicaActor)
        handle = cls.options(
            max_concurrency=cfg["max_ongoing"] + 8, **actor_options
        ).remote(
            cfg["serialized_callable"], cfg["init_args"], cfg["init_kwargs"],
            cfg.get("user_config"), state.name, state.app_name, replica_id,
        )
        r = _Replica(replica_id, version, handle, handle._actor_id)
        r.applied_user_config = cfg.get("user_config")
        with self._lock:
            state.replicas.append(r)
        logger.info("starting replica %s (version %s)", replica_id, version[:8])

    def _drain_replica(self, r: _Replica) -> None:
        """Stop routing to the replica; it is killed once its in-flight
        requests complete (graceful_shutdown_wait_loop in the reference)."""
        if r.state != STOPPING:
            r.state = STOPPING
            r.draining_since = chaos_clock.now()

    # ----------------------------------------------------------- autoscaling
    def _record_scale_event(self, state: _DeploymentState, old: int, new: int,
                            trigger: str, value, target) -> None:
        """Every scale decision becomes (a) a history row in
        ``get_app_status()`` / ``cli serve status`` and (b) a span in the
        trace store, so 'why did we scale at 12:04' is answerable from
        either surface."""
        now = time.time()
        event = {
            "ts": now, "from": old, "to": new, "trigger": trigger,
            "value": None if value is None else round(float(value), 2),
            "target": target,
        }
        state.scale_events.append(event)
        del state.scale_events[:-50]
        logger.info("autoscale %s: %d -> %d (%s=%s target=%s)",
                    state.name, old, new, trigger, event["value"], target)
        try:
            from ..observability import tracing

            span = tracing.make_span(
                f"serve.autoscale {state.name}", "serve", now, now,
                tracing.new_trace_id(),
                attrs={"deployment": state.name, "app": state.app_name,
                       "from": old, "to": new, "trigger": trigger,
                       "value": event["value"], "target": target})
            tracing.record_span(span)
        except Exception:
            pass

    def _autoscale_from_probes(self, state: _DeploymentState, probes: dict) -> None:
        auto = state.config.get("autoscaling")
        if not auto or state.config.get("deleted"):
            return
        running = [r for r in state.replicas if r.state == RUNNING]
        if not running:
            return
        if auto.get("mode") == "latency_slo":
            self._autoscale_latency_slo(state, auto, running, probes)
            return
        self._autoscale_queue_based(state, auto, running, probes)

    def _autoscale_queue_based(self, state: _DeploymentState, auto: dict,
                               running: list, probes: dict) -> None:
        """Queue-based autoscaling (reference autoscaling_state.py): desired
        replicas = ceil(total ongoing / target_ongoing_requests), clamped,
        with separate up/downscale delays."""
        total = float(sum(probes.get(r.replica_id, {}).get("queue", 0) for r in running))
        now = time.time()
        state.autoscale_history.append((now, total))
        state.autoscale_history = [(t, v) for t, v in state.autoscale_history if now - t <= 30.0]
        desired = math.ceil(total / auto["target_ongoing_requests"]) if total > 0 else auto["min_replicas"]
        desired = max(auto["min_replicas"], min(auto["max_replicas"], desired))
        cur = state.target_replicas
        if desired > cur and now - state.last_scale_up >= auto["upscale_delay_s"]:
            state.target_replicas = desired
            state.last_scale_up = now
            self._record_scale_event(state, cur, desired, "ongoing_requests",
                                     total, auto["target_ongoing_requests"])
        elif desired < cur and now - state.last_scale_down >= auto["downscale_delay_s"]:
            state.target_replicas = desired
            state.last_scale_down = now
            self._record_scale_event(state, cur, desired, "ongoing_requests",
                                     total, auto["target_ongoing_requests"])

    @staticmethod
    def _merge_latency_rows(probes: dict) -> dict:
        """Sum each latency histogram across replica probe snapshots:
        {metric_name: (buckets, boundaries, count)}."""
        merged: dict[str, tuple[list[int], list[float], int]] = {}
        for p in probes.values():
            for row in p.get("latency") or []:
                buckets = list(row.get("buckets") or [])
                if not buckets:
                    continue
                name = row["name"]
                cur = merged.get(name)
                if cur is None:
                    merged[name] = (buckets, list(row.get("boundaries") or []),
                                    int(row.get("count", 0)))
                else:
                    summed = [a + b for a, b in zip(cur[0], buckets)]
                    merged[name] = (summed, cur[1],
                                    cur[2] + int(row.get("count", 0)))
        return merged

    def _windowed_quantile(self, state: _DeploymentState, metric: str,
                           q: float, window_s: float, now: float):
        """Quantile of the observations that landed within the window:
        delta of the cumulative merged histogram vs the snapshot at the
        window's start (replica restarts can shrink counts — negative
        deltas clamp to 0). None = no traffic in the window."""
        from ..util.metrics import histogram_quantile

        latest = state.latency_history[-1][1].get(metric) if state.latency_history else None
        if latest is None:
            return None
        base = None
        for ts, snap in state.latency_history[:-1]:
            if now - ts <= window_s:
                break
            if metric in snap:
                base = snap[metric]
        buckets, boundaries, _ = latest
        if base is not None:
            buckets = [max(0, a - b) for a, b in zip(buckets, base[0])]
        if sum(buckets) == 0:
            return None
        return histogram_quantile(
            {"buckets": buckets, "boundaries": boundaries}, q)

    def _autoscale_latency_slo(self, state: _DeploymentState, auto: dict,
                               running: list, probes: dict) -> None:
        """Latency-SLO autoscaling: scale from the windowed TTFT quantile
        the replicas actually served (the PR-2 ``serve_ttft_ms`` /
        ``serve_queue_wait_ms`` histograms) instead of the queue-depth
        proxy. Hysteresis = ``breach_cycles`` consecutive breaching (or
        clear) probe rounds AND the up/downscale delay debounce."""
        now = time.time()
        merged = self._merge_latency_rows(probes)
        if auto.get("target_queue_wait_ms") is not None \
                and "serve_queue_wait_ms" not in merged:
            # Queue wait is observed router-side (proxy/driver processes),
            # so the replica probes never carry it — pull the cluster
            # aggregate from the GCS instead (flushed every ~5 s; fine
            # for a windowed quantile).
            try:
                from ..util.metrics import get_metrics

                for m in get_metrics():
                    if (m["name"] == "serve_queue_wait_ms" and m.get("buckets")
                            and m.get("tags", {}).get("deployment")
                            == state.name):
                        cur = merged.get("serve_queue_wait_ms")
                        buckets = list(m["buckets"])
                        if cur is not None:
                            buckets = [a + b for a, b in zip(cur[0], buckets)]
                        merged["serve_queue_wait_ms"] = (
                            buckets, list(m.get("boundaries") or []),
                            int(m.get("count", 0)) + (cur[2] if cur else 0))
            except Exception:
                pass
        state.latency_history.append((now, merged))
        window = float(auto.get("latency_window_s") or 30.0)
        state.latency_history = [
            (t, s) for t, s in state.latency_history if now - t <= 2 * window]
        q = float(auto.get("slo_quantile") or 0.95)
        target_ttft = float(auto.get("target_ttft_ms") or 500.0)
        p_ttft = self._windowed_quantile(state, "serve_ttft_ms", q, window, now)
        target_qw = auto.get("target_queue_wait_ms")
        p_qw = (self._windowed_quantile(state, "serve_queue_wait_ms", q,
                                        window, now)
                if target_qw else None)
        # Worst-tenant windowed TTFT p95 from the folded ``serve_tenancy``
        # rows: a single tenant breaching the SLO must scale the
        # deployment even when the aggregate histogram is diluted by a
        # healthy majority (the noisy-neighbor blind spot).
        tenant_p95 = None
        for t_row in (state.tenancy.get("tenants") or {}).values():
            t95 = t_row.get("p95_ttft_ms")
            if t95 is not None:
                tenant_p95 = max(float(t95), tenant_p95 or 0.0)
        # Predictive upscale (fleet round): extrapolate the windowed TTFT
        # trend ``predictive_horizon_s`` ahead — a projected breach counts
        # as a breach NOW, so capacity promotes before the p95 crosses
        # the SLO instead of after.
        pred_ttft = None
        if auto.get("predictive"):
            state.ttft_trend.append((now, p_ttft))
            state.ttft_trend = [
                (t, v) for t, v in state.ttft_trend if now - t <= 2 * window]
            pred_ttft = fleet_policy.slope_projection(
                state.ttft_trend,
                float(auto.get("predictive_horizon_s") or 10.0))
        pred_breach = pred_ttft is not None and pred_ttft > target_ttft
        ttft_breach = p_ttft is not None and p_ttft > target_ttft
        qw_breach = (target_qw is not None and p_qw is not None
                     and p_qw > float(target_qw))
        tenant_breach = tenant_p95 is not None and tenant_p95 > target_ttft
        breach = ttft_breach or qw_breach or tenant_breach or pred_breach
        headroom = float(auto.get("downscale_headroom") or 0.5)
        clear = (not pred_breach) and (
            p_ttft is None or p_ttft < headroom * target_ttft) and (
            target_qw is None or p_qw is None or p_qw < headroom * float(target_qw)) and (
            tenant_p95 is None or tenant_p95 < headroom * target_ttft)
        state.slo_breach_streak = state.slo_breach_streak + 1 if breach else 0
        state.slo_ok_streak = state.slo_ok_streak + 1 if clear else 0
        cycles = max(1, int(auto.get("breach_cycles") or 1))
        cur = state.target_replicas
        if qw_breach:
            trigger = "serve_queue_wait_ms_p%d" % round(100 * q)
            value, target = p_qw, float(target_qw)
        elif tenant_breach and not ttft_breach:
            trigger = "tenant_ttft_ms_p95"
            value, target = tenant_p95, target_ttft
        elif pred_breach and not ttft_breach:
            trigger = "predicted_ttft_ms"
            value, target = pred_ttft, target_ttft
        else:
            trigger = "serve_ttft_ms_p%d" % round(100 * q)
            value, target = p_ttft, target_ttft
        if (breach and cur < auto["max_replicas"]
                and state.slo_breach_streak >= cycles
                and now - state.last_scale_up >= auto["upscale_delay_s"]):
            state.target_replicas = cur + 1
            state.last_scale_up = now
            state.slo_breach_streak = 0
            self._record_scale_event(state, cur, cur + 1, trigger, value, target)
        elif (clear and cur > auto["min_replicas"]
                and state.slo_ok_streak >= cycles
                and now - state.last_scale_down >= auto["downscale_delay_s"]):
            state.target_replicas = cur - 1
            state.last_scale_down = now
            state.slo_ok_streak = 0
            self._record_scale_event(
                state, cur, cur - 1, "serve_ttft_ms_p%d" % round(100 * q),
                p_ttft, target_ttft)

    # ------------------------------------------------------------- push/ckpt
    def _push_replica_table(self, state: _DeploymentState) -> None:
        table = [
            {
                "replica_id": r.replica_id,
                "actor_id": r.actor_id.hex(),
                "max_ongoing": state.config["max_ongoing"],
            }
            for r in state.replicas
            if r.state == RUNNING
        ]
        self._long_poll.notify_changed(f"replicas::{state.app_name}::{state.name}", table)

    def _push_tenancy(self, state: _DeploymentState) -> None:
        """Publish the deployment's tenant weights — and the folded
        retire-time cost-correction ratios — on the ``tenancy::``
        long-poll key so every router's weighted-fair queue uses the
        same shares the replicas' quota ledgers were configured with and
        scales its token-cost estimates by observed reality."""
        tcfg = (state.config.get("init_kwargs") or {}).get("tenancy_config")
        weights = {}
        if tcfg:
            try:
                from ..llm.tenancy import TenancyConfig

                cfg = TenancyConfig.from_dict(tcfg)
                weights = cfg.weights() if cfg is not None else {}
            except Exception:
                logger.warning("bad tenancy_config for %s", state.name)
        correction = {
            t: row["cost_correction"]
            for t, row in ((state.tenancy or {}).get("tenants") or {}).items()
            if row.get("cost_correction") is not None}
        self._long_poll.notify_changed(
            f"tenancy::{state.app_name}::{state.name}",
            {"weights": weights, "cost_correction": correction})

    def _push_routes(self) -> None:
        self._long_poll.notify_changed(
            "routes", [{"prefix": p, "app": a, "deployment": d} for p, (a, d) in self._routes.items()]
        )

    def _checkpoint(self) -> None:
        with self._lock:
            blob = cloudpickle.dumps({
                "routes": self._routes,
                "apps": {
                    app: {
                        name: {
                            "config": s.config,
                            "target": s.target_replicas,
                            "replicas": [
                                (r.replica_id, r.version, r.actor_id, r.state)
                                for r in s.replicas
                            ],
                            "next_no": s.next_replica_no,
                            "scaled_to_zero": s.scaled_to_zero,
                        }
                        for name, s in deps.items()
                    }
                    for app, deps in self._apps.items()
                },
            })
        try:
            ray.global_worker()._gcs_call("KvPut", {"key": CHECKPOINT_KEY, "value": blob, "overwrite": True})
        except Exception:
            pass

    def _recover(self) -> None:
        """Re-adopt replicas from the checkpoint after a controller restart
        (reference: controller recovers DeploymentStateManager from the
        checkpointed state)."""
        from ..core.api import ActorHandle

        try:
            reply = ray.global_worker()._gcs_call("KvGet", {"key": CHECKPOINT_KEY})
        except Exception:
            return
        if not reply.get("found"):
            return
        data = cloudpickle.loads(reply["value"])
        self._routes = data["routes"]
        for app, deps in data["apps"].items():
            self._apps[app] = {}
            for name, saved in deps.items():
                state = _DeploymentState(app, saved["config"])
                state.target_replicas = saved["target"]
                state.next_replica_no = saved["next_no"]
                # Older checkpoints predate the fleet fields — .get keeps
                # them adoptable.
                state.scaled_to_zero = bool(saved.get("scaled_to_zero"))
                for replica_id, version, actor_id, rstate in saved["replicas"]:
                    # STANDBY replicas are re-adopted too: their host-RAM
                    # weights and warm compile cache survive a controller
                    # restart (the replica actor never died).
                    if rstate not in (RUNNING, STANDBY):
                        continue
                    try:
                        handle = ActorHandle(actor_id)
                        r = _Replica(replica_id, version, handle, actor_id)
                        r.state = rstate
                        state.replicas.append(r)
                    except Exception:
                        pass
                self._apps[app][name] = state
                self._push_replica_table(state)
        self._push_routes()
        logger.info("serve controller recovered %d app(s) from checkpoint", len(self._apps))
