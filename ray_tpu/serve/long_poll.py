"""Long-poll config push: controller → routers/proxies.

Reference: ``python/ray/serve/_private/long_poll.py:204`` (LongPollHost) —
clients ask "anything newer than snapshot N for these keys?" and the host
parks the request until an update lands or a timeout fires. This replaces
polling for routing tables: a replica-set change reaches every router in
one RTT.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable


class LongPollHost:
    """Lives inside the Serve controller actor."""

    def __init__(self, poll_timeout_s: float = 5.0):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._snapshot_ids: dict[str, int] = {}
        self._objects: dict[str, Any] = {}
        self._poll_timeout_s = poll_timeout_s

    def notify_changed(self, key: str, obj: Any) -> None:
        with self._cond:
            self._snapshot_ids[key] = self._snapshot_ids.get(key, 0) + 1
            self._objects[key] = obj
            self._cond.notify_all()

    def listen_for_change(self, keys_to_snapshot_ids: dict[str, int]) -> dict:
        """Block until any key moves past the client's snapshot (or time
        out, returning {}). Returns {key: {"snapshot_id", "object"}}."""
        deadline = time.monotonic() + self._poll_timeout_s
        with self._cond:
            while True:
                out = {}
                for key, seen in keys_to_snapshot_ids.items():
                    cur = self._snapshot_ids.get(key, 0)
                    if cur > seen:
                        out[key] = {"snapshot_id": cur, "object": self._objects.get(key)}
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._cond.wait(remaining)

    def get(self, key: str) -> tuple[int, Any]:
        with self._lock:
            return self._snapshot_ids.get(key, 0), self._objects.get(key)


class LongPollClient:
    """Runs a daemon thread long-polling the controller for a set of keys.

    ``callbacks``: {key: fn(object)} invoked on each update (and once with
    the current value at startup).
    """

    def __init__(self, controller_handle, callbacks: dict[str, Callable[[Any], None]]):
        from ..core import api as ray

        self._ray = ray
        self._controller = controller_handle
        self._callbacks = callbacks
        self._snapshots = {key: 0 for key in callbacks}
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serve-longpoll")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                updates = self._ray.get(
                    self._controller.listen_for_change.remote(dict(self._snapshots)),
                    timeout=30.0,
                )
            except Exception:
                if self._stopped.is_set():
                    return
                time.sleep(0.2)
                continue
            for key, update in (updates or {}).items():
                self._snapshots[key] = update["snapshot_id"]
                try:
                    self._callbacks[key](update["object"])
                except Exception:
                    pass

    def stop(self) -> None:
        self._stopped.set()
