"""Public Serve API: serve.run / serve.delete / serve.status / handles.

Reference: ``python/ray/serve/api.py`` (run:571, delete, status) and
``_private/client.py``. The controller is a detached named actor; the
proxy is created on demand with ``serve.start(http_options=...)`` or the
first ``serve.run``.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any

import cloudpickle

from ..core import api as ray
from .deployment import Application, AutoscalingConfig, Deployment
from .router import CONTROLLER_NAME, HANDLE_MARKER, DeploymentHandle

_PROXY_NAME = "SERVE_PROXY"


def _get_or_create_controller():
    try:
        return ray.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    from .controller import ServeController

    handle = ray.remote(ServeController).options(
        name=CONTROLLER_NAME, lifetime="detached", num_cpus=0, max_concurrency=64
    ).remote()
    # wait until it serves requests
    ray.get(handle.list_deployments.remote(), timeout=60)
    return handle


def start(http_options: dict | None = None):
    """Ensure the Serve instance (controller + HTTP proxy) is running."""
    controller = _get_or_create_controller()
    try:
        proxy = ray.get_actor(_PROXY_NAME)
    except ValueError:
        from .http_proxy import ProxyActor

        opts = http_options or {}
        proxy = ray.remote(ProxyActor).options(
            name=_PROXY_NAME, lifetime="detached", num_cpus=0, max_concurrency=32
        ).remote(opts.get("host", "127.0.0.1"), opts.get("port", 0))
        ray.get(proxy.ready.remote(), timeout=60)
        ray.get(controller.register_proxy.remote(proxy._actor_id), timeout=30)
    return controller


def http_address() -> str:
    proxy = ray.get_actor(_PROXY_NAME)
    return ray.get(proxy.address.remote(), timeout=30)


def _encode_arg(arg: Any, app_name: str):
    if isinstance(arg, Application):
        return {"t": HANDLE_MARKER, "app": app_name, "deployment": arg.deployment.name}
    return arg


def _deployment_config(app: Application, app_name: str) -> dict:
    d = app.deployment
    serialized = cloudpickle.dumps(d.func_or_class)
    init_args = tuple(_encode_arg(a, app_name) for a in app.init_args)
    init_kwargs = {k: _encode_arg(v, app_name) for k, v in app.init_kwargs.items()}
    auto = d.autoscaling_config
    # user_config is EXCLUDED from the version: config-only changes apply
    # in place via replica.reconfigure, not a rolling restart.
    version_src = serialized + cloudpickle.dumps((init_args, init_kwargs, d.num_replicas, d.max_ongoing_requests))
    return {
        "name": d.name,
        "serialized_callable": serialized,
        "init_args": init_args,
        "init_kwargs": init_kwargs,
        "num_replicas": d.num_replicas,
        "max_ongoing": d.max_ongoing_requests,
        "user_config": getattr(d, "user_config", None),
        "pool": getattr(d, "pool", None),
        "ray_actor_options": d.ray_actor_options,
        "autoscaling": (
            {
                "min_replicas": auto.min_replicas,
                "max_replicas": auto.max_replicas,
                "target_ongoing_requests": auto.target_ongoing_requests,
                "upscale_delay_s": auto.upscale_delay_s,
                "downscale_delay_s": auto.downscale_delay_s,
                "mode": auto.mode,
                "target_ttft_ms": auto.target_ttft_ms,
                "target_queue_wait_ms": auto.target_queue_wait_ms,
                "latency_window_s": auto.latency_window_s,
                "slo_quantile": auto.slo_quantile,
                "downscale_headroom": auto.downscale_headroom,
                "breach_cycles": auto.breach_cycles,
                "standby_replicas": auto.standby_replicas,
                "scale_to_zero_idle_s": auto.scale_to_zero_idle_s,
                "scheduled_capacity": auto.scheduled_capacity,
                "predictive": auto.predictive,
                "predictive_horizon_s": auto.predictive_horizon_s,
            }
            if auto
            else None
        ),
        "version": hashlib.sha1(version_src).hexdigest(),
    }


def run(app: Application, *, name: str = "default", route_prefix: str | None = "/",
        _blocking: bool = True, timeout_s: float = 120.0,
        _local_testing_mode: bool = False) -> DeploymentHandle:
    """Deploy an application and wait for it to be healthy. Reference:
    serve/api.py run().

    ``_local_testing_mode=True`` instantiates the deployments in THIS
    process and returns a local handle — no controller, proxy, or actors
    (reference ``serve/_private/local_testing_mode.py``). For unit
    tests of handler logic."""
    if _local_testing_mode:
        from .local_testing_mode import make_local_deployment_handle

        return make_local_deployment_handle(app, name)
    controller = start()
    nodes = app.walk()
    configs = [_deployment_config(node, name) for node in nodes]
    ingress = app.deployment.name
    ray.get(
        controller.deploy_application.remote(name, route_prefix, configs, ingress),
        timeout=60,
    )
    if _blocking:
        deadline = time.monotonic() + timeout_s
        while True:
            status = ray.get(controller.get_app_status.remote(name), timeout=30)
            live = {k: v for k, v in status.items() if not v["deleted"]}
            if live and all(v["healthy"] for v in live.values()):
                break
            if time.monotonic() > deadline:
                raise TimeoutError(f"application {name!r} not healthy in {timeout_s}s: {status}")
            time.sleep(0.2)
    return DeploymentHandle(name, ingress)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = ray.get_actor(CONTROLLER_NAME)
    deps = ray.get(controller.list_deployments.remote(), timeout=30)
    if name not in deps:
        raise ValueError(f"no Serve application named {name!r}")
    routes = {r["app"]: r["deployment"] for r in (ray.get(controller.get_snapshot.remote("routes"), timeout=30) or [])}
    ingress = routes.get(name) or next(iter(deps[name]))
    return DeploymentHandle(name, ingress)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> dict:
    controller = ray.get_actor(CONTROLLER_NAME)
    deps = ray.get(controller.list_deployments.remote(), timeout=30)
    out = {
        app: ray.get(controller.get_app_status.remote(app), timeout=30) for app in deps
    }
    # Fold in the HTTP proxy's router-side overload view (front-door
    # sheds by reason, router-queue deadline expiries, circuit states):
    # the replica probes only see requests that reached a replica.
    try:
        proxy = ray.get_actor(_PROXY_NAME)
        stats = ray.get(proxy.overload_stats.remote(), timeout=10)
        for app, dep_stats in (stats or {}).items():
            for dep, snap in dep_stats.items():
                slot = out.get(app, {}).get(dep)
                if slot is not None:
                    slot.setdefault("overload", {})["router"] = snap
    except Exception:
        pass
    return out


def update_tenancy_config(tenancy_config: dict, *, app_name: str = "default",
                          deployment_name: str | None = None) -> dict:
    """Live-reconfigure a deployment's tenant WFQ weights/quotas without
    a redeploy: the controller swaps the stored ``tenancy_config`` and
    re-publishes the folded weights long-poll key, so every router picks
    the change up on its next poll (PR 16 residue c). Returns the
    controller's ``{"updated": [deployment names]}`` summary."""
    controller = ray.get_actor(CONTROLLER_NAME)
    return ray.get(
        controller.update_tenancy_config.remote(
            app_name, deployment_name, tenancy_config),
        timeout=30)


def delete(name: str) -> None:
    controller = ray.get_actor(CONTROLLER_NAME)
    ray.get(controller.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    """Tear down the whole Serve instance (controller, proxy, replicas)."""
    try:
        controller = ray.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray.get(controller.graceful_shutdown.remote(), timeout=60)
    except Exception:
        pass
    try:
        ray.kill(controller)
    except Exception:
        pass
    try:
        proxy = ray.get_actor(_PROXY_NAME)
        ray.kill(proxy)
    except Exception:
        pass
