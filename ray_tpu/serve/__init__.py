"""ray_tpu.serve: model serving.

Reference: ``python/ray/serve/`` (SURVEY.md §2.3/§3.5): controller actor
reconciling a replica FSM with rolling updates, per-process routers with
power-of-two replica choice, long-poll config push, queue-based
autoscaling, and an HTTP ingress proxy.
"""

from .api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    http_address,
    run,
    shutdown,
    start,
    status,
    update_tenancy_config,
)
from .batching import batch
from .config_api import build_app_from_spec, deploy_config, serve_status
from .local_testing_mode import make_local_deployment_handle
from .grpc_proxy import start_grpc
from .deployment import Application, AutoscalingConfig, Deployment, deployment
from .multiplex import get_multiplexed_model_id, multiplexed
from .replica import Request
from .router import (
    DeadlineExceeded,
    DeploymentHandle,
    DeploymentResponse,
    DeploymentStreamingResponse,
    RequestShed,
    get_request_deadline,
)

__all__ = [
    "Application",
    "AutoscalingConfig",
    "DeadlineExceeded",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentStreamingResponse",
    "RequestShed",
    "get_request_deadline",
    "Request",
    "batch",
    "build_app_from_spec",
    "deploy_config",
    "serve_status",
    "start_grpc",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "http_address",
    "make_local_deployment_handle",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "status",
    "update_tenancy_config",
]
