"""Fleet policy: the pure decision half of the always-warm serving
fleet (ROADMAP item 5).

The controller's reconcile loop stays readable by keeping every fleet
decision a pure function over probe-derived state: how many standbys a
deployment wants, what the scheduled-capacity floor is right now,
whether the recent TTFT trend projects past the SLO (predictive
upscale), and whether an idle deployment should fall to standby or to
host-RAM-only. The controller (``serve/controller.py``) owns the FSM —
STANDBY replicas hold weights in host RAM with a warm compile cache and
promote via ``device_put`` (``llm/weights.py``) — this module only
answers "what should the fleet look like".

Scheduled capacity entries are dicts with absolute unix times::

    {"start": <unix>, "end": <unix>, "min_replicas": N}

so operators can pre-arm capacity for a known spike (a product launch,
a batch window) and promotion fires before the first request, not after
the p95 breach.
"""

from __future__ import annotations


def _cfg_get(auto, key: str, default=None):
    """Read a knob off an AutoscalingConfig object OR the plain dict the
    controller stores (serve/api.py serializes the dataclass)."""
    if auto is None:
        return default
    if isinstance(auto, dict):
        val = auto.get(key, default)
    else:
        val = getattr(auto, key, default)
    return default if val is None else val


def scheduled_floor(entries, now: float) -> int:
    """The largest ``min_replicas`` of every scheduled-capacity window
    covering ``now`` (0 when none do). Malformed entries are skipped —
    a bad schedule must never wedge the reconcile loop."""
    floor = 0
    for ent in entries or ():
        try:
            if float(ent["start"]) <= now < float(ent["end"]):
                floor = max(floor, int(ent["min_replicas"]))
        except (KeyError, TypeError, ValueError):
            continue
    return floor


def slope_projection(samples, horizon_s: float) -> float | None:
    """Project a metric ``horizon_s`` ahead by least-squares slope over
    ``samples`` = [(ts, value), ...]. Returns None with fewer than 3
    points or a degenerate time spread — prediction needs a trend, not
    two noisy dots."""
    pts = [(float(t), float(v)) for t, v in (samples or ())
           if v is not None]
    if len(pts) < 3:
        return None
    n = len(pts)
    t0 = pts[0][0]
    xs = [t - t0 for t, _ in pts]
    ys = [v for _, v in pts]
    span = xs[-1] - xs[0]
    if span <= 1e-6:
        return None
    mx = sum(xs) / n
    my = sum(ys) / n
    den = sum((x - mx) ** 2 for x in xs)
    if den <= 1e-9:
        return None
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / den
    return ys[-1] + slope * float(horizon_s)


def desired_standby(auto) -> int:
    """How many STANDBY replicas a deployment keeps warm. With
    scale-to-zero enabled a deployment always affords at least one
    standby slot (else the first request pays a full cold start, which
    defeats the feature)."""
    if auto is None:
        return 0
    n = int(_cfg_get(auto, "standby_replicas", 0) or 0)
    if _cfg_get(auto, "scale_to_zero_idle_s"):
        n = max(n, 1)
    return max(0, n)


def should_scale_to_zero(idle_s: float | None, auto) -> bool:
    """True once a deployment's replicas have been request-idle past
    ``scale_to_zero_idle_s``. ``idle_s`` is None until every replica
    has reported an idle age (an unknown replica might be busy)."""
    if auto is None or idle_s is None:
        return False
    thresh = _cfg_get(auto, "scale_to_zero_idle_s")
    if not thresh or float(thresh) <= 0:
        return False
    return float(idle_s) >= float(thresh)


def fold_fleet_rows(rows) -> dict | None:
    """Fold per-replica ``serve_fleet`` probe rows into the deployment
    view the controller's decision phase consumes: the fleet is only as
    idle as its BUSIEST replica (min idle age), and weight residency
    counts report how much of the fleet could demote at all."""
    idle = None
    unknown = False
    residency_capable = 0
    host_resident = 0
    n = 0
    for row in rows or ():
        if not isinstance(row, dict):
            continue
        n += 1
        age = row.get("idle_s")
        if age is None:
            # One replica with unknown idleness poisons the fold: we
            # must not scale-to-zero under it.
            unknown = True
        else:
            idle = float(age) if idle is None else min(idle, float(age))
        if row.get("residency_capable"):
            residency_capable += 1
        if row.get("weights_on_host"):
            host_resident += 1
    if n == 0:
        return None
    return {"idle_s": None if unknown else idle, "replicas": n,
            "residency_capable": residency_capable,
            "host_resident": host_resident}
