"""Deployment definitions and application graphs.

Reference: ``python/ray/serve/api.py`` (@serve.deployment),
``deployment.py``, ``build_app.py``. A Deployment wraps a user class or
function with replica/autoscaling settings; ``bind()`` produces an
Application node whose init args may contain other bound deployments
(composed into handles at deploy time).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class AutoscalingConfig:
    """Replica autoscaling policy.

    ``mode="ongoing_requests"`` (default) is the reference's queue-based
    policy: desired = ceil(total ongoing / target_ongoing_requests).

    ``mode="latency_slo"`` scales directly from the serving latency SLO:
    the controller pulls each replica's local ``serve_ttft_ms`` histogram
    through the probe path, computes the windowed ``slo_quantile`` (p95
    by default) over ``latency_window_s``, and steps the replica count up
    when it breaches ``target_ttft_ms`` (or ``target_queue_wait_ms``
    against the cluster ``serve_queue_wait_ms`` histogram, when set) and
    down when it sits below ``downscale_headroom * target``. Hysteresis:
    a breach/clear must persist ``breach_cycles`` consecutive probe
    rounds AND the up/downscale delays still debounce, so one slow
    request never doubles the fleet."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # --- latency_slo mode ---
    mode: str = "ongoing_requests"
    target_ttft_ms: float = 500.0
    target_queue_wait_ms: float | None = None
    latency_window_s: float = 30.0
    slo_quantile: float = 0.95
    downscale_headroom: float = 0.5
    breach_cycles: int = 2
    # --- always-warm fleet (serve/fleet.py) ---
    # Replicas kept STANDBY: started, compile cache warm, weights in
    # host RAM. Promotion to RUNNING is a device_put, not a cold start.
    standby_replicas: int = 0
    # After this many request-idle seconds the deployment demotes every
    # RUNNING replica to standby (first request promotes one back).
    # None/0 disables scale-to-zero.
    scale_to_zero_idle_s: float | None = None
    # [{"start": unix, "end": unix, "min_replicas": N}, ...]: capacity
    # floors for known spikes, applied before any breach is observed.
    scheduled_capacity: list | None = None
    # Predictive upscale (latency_slo mode): project the windowed TTFT
    # quantile ``predictive_horizon_s`` ahead by its rate of change and
    # scale up when the PROJECTION breaches — before the p95 does.
    predictive: bool = False
    predictive_horizon_s: float = 10.0


class Deployment:
    def __init__(
        self,
        func_or_class: Any,
        *,
        name: str | None = None,
        num_replicas: int | None = None,
        max_ongoing_requests: int = 8,
        autoscaling_config: AutoscalingConfig | dict | None = None,
        ray_actor_options: dict | None = None,
        user_config: Any = None,
        pool: str | None = None,
    ):
        self.func_or_class = func_or_class
        self.name = name or getattr(func_or_class, "__name__", "deployment")
        self.num_replicas = num_replicas or 1
        self.max_ongoing_requests = max_ongoing_requests
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        self.autoscaling_config = autoscaling_config
        self.ray_actor_options = ray_actor_options or {}
        self.user_config = user_config
        # Pool membership label for disaggregated apps (e.g. "prefill" /
        # "decode"): pure metadata, surfaced in serve.status() so pool
        # topology is observable; routing never reads it.
        self.pool = pool

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            name=self.name,
            num_replicas=self.num_replicas,
            max_ongoing_requests=self.max_ongoing_requests,
            autoscaling_config=self.autoscaling_config,
            ray_actor_options=self.ray_actor_options,
            user_config=self.user_config,
            pool=self.pool,
        )
        merged.update(kwargs)
        return Deployment(self.func_or_class, **merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name}, replicas={self.num_replicas})"


class Application:
    """A bound deployment DAG node. Reference: serve's built app graph."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    def walk(self) -> list["Application"]:
        """All Application nodes reachable from this one (deps first)."""
        seen: list[Application] = []

        def visit(node: Application):
            for a in list(node.init_args) + list(node.init_kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            if node not in seen:
                seen.append(node)

        visit(self)
        return seen


def deployment(_func_or_class: Any = None, **kwargs) -> Any:
    """@serve.deployment decorator / factory. Reference: serve/api.py.
    Both forms carry their options: ``@serve.deployment(num_replicas=2)``
    and ``serve.deployment(Cls, num_replicas=2)``."""
    if _func_or_class is not None:
        return Deployment(_func_or_class, **kwargs)

    def wrap(fc):
        return Deployment(fc, **kwargs)

    return wrap
