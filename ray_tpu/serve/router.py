"""Router + DeploymentHandle: request scheduling onto replicas.

Reference: ``python/ray/serve/_private/router.py:321`` and
``replica_scheduler/pow_2_scheduler.py:52`` — the router keeps a local
view of each replica's in-flight count, samples two replicas at random
and picks the less loaded one, skipping replicas at their
``max_ongoing_requests`` cap (backpressure: the caller queues until a
slot frees). Replica membership arrives via long-poll from the
controller, so scale-ups and rolling updates apply without polling.
"""

from __future__ import annotations

import hashlib
import random
import threading
from collections import OrderedDict
from typing import Any

from ..core import api as ray
from ..core.worker import global_worker
from .long_poll import LongPollClient

HANDLE_MARKER = "__serve_handle_marker__"

CONTROLLER_NAME = "SERVE_CONTROLLER"

# Spill migration (KV migration, degenerate single-pool case): when a
# prefix-group request spills off its affine replica, the router ships
# the OLD replica's identity along with the request so the spill target
# can pull the group's hot KV pages instead of cold-prefilling them.
# Travels as a reserved kwarg (popped by the replica before the user
# callable sees it) and surfaces through a thread-local, mirroring the
# multiplexed-model-id plumbing.
MIGRATE_FROM_KWARG = "_serve_migrate_from"

# End-to-end request deadline (overload protection): an absolute wall
# clock (time.time()) stamped at proxy ingress from the
# `x-raytpu-deadline-ms` header / `timeout_s` body field /
# `serve_default_deadline_s` config, threaded router → replica queue →
# engine admission → mid-stream decode. Travels as a reserved kwarg
# (popped by the replica before the user callable sees it) and surfaces
# through a thread-local, exactly like the multiplexed-model-id.
DEADLINE_KWARG = "_serve_deadline"

_migration_context = threading.local()
_deadline_context = threading.local()


def set_request_deadline(deadline: float | None) -> None:
    """Install the current request's absolute wall-clock deadline for
    this request thread (called by the replica before invoking the user
    callable); None = no deadline."""
    _deadline_context.deadline = deadline


def get_request_deadline() -> float | None:
    """Inside a request: the absolute ``time.time()`` deadline the proxy
    stamped at ingress, or None when the request carries none."""
    return getattr(_deadline_context, "deadline", None)


class RequestShed(RuntimeError):
    """The request was refused by overload protection (bounded queue,
    circuit breaker, replica exhaustion) — an honest fast 503, not a
    failure of the request itself. ``retry_after`` derives from the
    observed per-replica service rate."""

    http_status = "503 Service Unavailable"

    def __init__(self, message: str, reason: str = "overload",
                 retry_after: int = 1):
        super().__init__(message)
        self.reason = reason
        self.retry_after = max(1, int(retry_after))


class DeadlineExceeded(TimeoutError):
    """The request's end-to-end deadline expired (here: while still
    queued in the router, before any replica was touched)."""

    http_status = "504 Gateway Timeout"


def set_migration_source(src: dict | None) -> None:
    """Install the spill-migration source ({"replica_id", "actor_id"} or
    None) for the current request thread (called by the replica before
    invoking the user callable)."""
    _migration_context.source = src


def get_migration_source() -> dict | None:
    """Inside a request: the replica this request spilled away from —
    the one holding its prefix group's cached KV — or None."""
    return getattr(_migration_context, "source", None)

# Request metrics (reference: serve_num_router_requests /
# serve_deployment_processing_latency_ms in serve/_private/router.py) —
# lazily created so importing serve doesn't start the metrics flusher.
_metrics_lock = threading.Lock()
_metrics: dict = {}


def _serve_metrics():
    with _metrics_lock:
        if not _metrics:
            from ..util.metrics import Counter, Gauge, Histogram

            _metrics["requests"] = Counter(
                "serve_num_requests_total",
                "Requests routed to replicas", tag_keys=("deployment",))
            _metrics["errors"] = Counter(
                "serve_num_errors_total",
                "Requests that raised", tag_keys=("deployment",))
            _metrics["latency"] = Histogram(
                "serve_request_latency_ms",
                "End-to-end handle latency",
                boundaries=(1, 5, 25, 100, 250, 500, 1000, 5000, 30000),
                tag_keys=("deployment",))
            _metrics["queue_wait"] = Histogram(
                "serve_queue_wait_ms",
                "Time a request waits in the router for a replica slot",
                tag_keys=("deployment",))
            _metrics["affinity_hits"] = Counter(
                "serve_affinity_hits_total",
                "Requests routed to their prefix group's affine replica",
                tag_keys=("deployment",))
            _metrics["affinity_misses"] = Counter(
                "serve_affinity_misses_total",
                "Prefix-group requests whose affine replica vanished "
                "(died/removed) — the KV must cold-prefill elsewhere",
                tag_keys=("deployment",))
            _metrics["affinity_new_groups"] = Counter(
                "serve_affinity_new_groups_total",
                "First-seen prefix groups (not an affinity failure; "
                "excluded from the hit rate)", tag_keys=("deployment",))
            _metrics["affinity_spills"] = Counter(
                "serve_affinity_spills_total",
                "Prefix-group requests spilled off an overloaded affine "
                "replica (load-aware spill)", tag_keys=("deployment",))
            _metrics["affinity_hit_rate"] = Gauge(
                "serve_prefix_affinity_hit_rate",
                "Fraction of prefix-group requests that landed on their "
                "affine replica (0-1, since router start)",
                tag_keys=("deployment",))
            _metrics["spill_migrations"] = Counter(
                "serve_spill_migrations",
                "Affinity spills shipped with a migrate-from source: the "
                "spill target pulls the group's hot KV pages from the "
                "previous replica instead of cold-prefilling",
                tag_keys=("deployment",))
            _metrics["shed"] = Counter(
                "serve_shed_requests",
                "Requests shed by overload protection (bounded router "
                "queue, circuit breaker, replica exhaustion) — fast "
                "honest 503s instead of queue collapse",
                tag_keys=("deployment", "reason", "tenant"))
            _metrics["deadline_expired"] = Counter(
                "serve_deadline_expired",
                "Requests whose end-to-end deadline expired, by where "
                "they were when it did (queued = never touched a "
                "replica)", tag_keys=("deployment", "where"))
            _metrics["circuit_open"] = Counter(
                "serve_circuit_open_total",
                "Replica circuit-breaker open transitions (N consecutive "
                "handle timeouts)", tag_keys=("deployment",))
        return _metrics


def prefix_group_key(session_id: str = "", text: str = "",
                     n_chars: int | None = None) -> str:
    """Prefix-group key for affinity routing: an explicit session id
    wins; otherwise the hash of the prompt's first ``n_chars`` characters
    — under the byte tokenizer that IS the first token blocks, so
    requests sharing a system prompt land in one group. Empty when
    neither is present (no affinity)."""
    if session_id:
        return f"sess:{session_id}"
    if not text:
        return ""
    if n_chars is None:
        from ..core.config import get_config

        n_chars = get_config().serve_prefix_group_chars
    head = text[:n_chars].encode("utf-8", errors="ignore")
    return "pfx:" + hashlib.sha1(head).hexdigest()[:16]


def _assign_traced(router: "Router", metrics: dict, deployment: str,
                   model_id: str, prefix_group: str = "",
                   spill_out: dict | None = None,
                   deadline: float | None = None,
                   cost: float = 1.0) -> tuple[str, Any]:
    """Assign a replica, recording the router queue wait as both a
    histogram observation and (inside an active trace) a span."""
    import time as _time

    from ..observability import tracing

    t0w, t0m = _time.time(), _time.monotonic()
    try:
        replica_id, actor = router.assign_replica(
            model_id=model_id, prefix_group=prefix_group,
            spill_out=spill_out, deadline=deadline, cost=cost)
    finally:
        wait_ms = 1000 * (_time.monotonic() - t0m)
        metrics["queue_wait"].observe(wait_ms, tags={"deployment": deployment})
        ctx = tracing.current()
        if ctx is not None:
            tracing.record_span(tracing.make_span(
                f"router.queue {deployment}", "serve", t0w, _time.time(),
                ctx.trace_id, ctx.span_id,
                attrs={"deployment": deployment}))
    return replica_id, actor


def resolve_handle_markers(obj):
    """Replace deploy-time handle markers with live DeploymentHandles
    (composition: a deployment's init args may reference other
    deployments)."""
    if isinstance(obj, tuple):
        return tuple(resolve_handle_markers(o) for o in obj)
    if isinstance(obj, list):
        return [resolve_handle_markers(o) for o in obj]
    if isinstance(obj, dict):
        if obj.get("t") == HANDLE_MARKER:
            return DeploymentHandle(obj["app"], obj["deployment"])
        return {k: resolve_handle_markers(v) for k, v in obj.items()}
    return obj


class Router:
    """Per-process router for one deployment."""

    def __init__(self, app_name: str, deployment_name: str):
        self._key = f"replicas::{app_name}::{deployment_name}"
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # replica_id -> {"actor": ActorHandle, "max_ongoing": int}
        self._replicas: dict[str, dict] = {}
        self._inflight: dict[str, int] = {}
        # multiplexing cache affinity: model_id -> last replica that served it
        self._model_affinity: dict[str, str] = {}
        # Prefix/session affinity: group key -> replica whose engine holds
        # that group's KV prefix (bounded LRU; load-aware spill keeps a
        # hot replica from queue-blowing on affinity alone).
        self._group_affinity: OrderedDict[str, str] = OrderedDict()
        self.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                               "new_groups": 0}
        # Spills that shipped a migrate-from source with the request
        # (the KV moved instead of being recomputed).
        self.spill_migrations = 0
        self._init_overload_state()
        controller = ray.get_actor(CONTROLLER_NAME)
        # Kept for the first-request wake path: a request landing on an
        # empty replica table pokes the controller to un-park a
        # scaled-to-zero deployment (fire-and-forget, rate-limited).
        self._controller = controller
        self._wake_target = (app_name, deployment_name)
        self._last_wake_rpc = 0.0
        self._tenancy_key = f"tenancy::{app_name}::{deployment_name}"
        self._long_poll = LongPollClient(
            controller, {self._key: self._update_replicas,
                         self._tenancy_key: self._update_tenancy})
        # prime with the current table so the first request needn't wait a
        # full poll round-trip
        try:
            snap = ray.get(controller.get_snapshot.remote(self._key), timeout=30)
            if snap is not None:
                self._update_replicas(snap)
            tsnap = ray.get(
                controller.get_snapshot.remote(self._tenancy_key), timeout=30)
            if tsnap is not None:
                self._update_tenancy(tsnap)
        except Exception:
            pass

    def _init_overload_state(self) -> None:
        """Overload-protection state (split out so the bare-router test
        skeleton shares it): bounded wait queue with cost-aware shedding,
        per-replica circuit breaker, and the completion-rate window the
        503 Retry-After derives from."""
        from collections import deque as _deque

        from ..llm.tenancy import WeightedFairQueue

        # Requests currently blocked waiting for a replica slot:
        # [{"cheap": bool, "shed": bool, "tenant": str, "ticket": int}]
        # in arrival order. Over the serve_max_queued_requests bound, new
        # arrivals are shed — unless cost-aware shedding lets a cheap
        # (KV-cached) request preempt the queue slot of an expensive
        # (cold-suffix) waiter, or tenant-aware shedding lets a tenant
        # UNDER its weighted fair share of queue slots preempt the newest
        # waiter of the most over-share tenant.
        self._waiters: list[dict] = []
        # Weighted fair queueing among waiters (tenancy): under
        # saturation only the waiter holding the minimum virtual finish
        # time proceeds, so admitted throughput follows tenant weights.
        # Weights arrive via the tenancy:: long-poll key (empty = every
        # tenant weight 1.0 — FIFO-equivalent, the pre-tenancy behavior).
        self._wfq = WeightedFairQueue()
        self._tenant_weights: dict[str, float] = {}
        # Retire-time cost correction published by the controller (per
        # tenant, EWMA of actual/estimated token cost): scales the
        # estimated WFQ cost so tenants that systematically overrun
        # their max_tokens heuristic still pay their true share.
        self._cost_correction: dict[str, float] = {}
        # replica_id -> {"state": "closed"|"open"|"half_open",
        #                "failures": consecutive timeouts, "opened_at"}
        self._circuit: dict[str, dict] = {}
        # monotonic stamps of recent request completions (release()):
        # the observed service rate behind Retry-After.
        self._completions: "_deque[float]" = _deque()
        self.overload_stats = {"shed": {}, "shed_by_tenant": {},
                               "deadline_expired_queued": 0,
                               "circuit_opens": 0}

    def _update_tenancy(self, value: Any) -> None:
        """Long-poll push of the deployment's tenancy policy (published
        by the controller from the deployment's ``tenancy_config``):
        installs per-tenant WFQ weights."""
        weights = (value or {}).get("weights") if isinstance(value, dict) \
            else None
        correction = (value or {}).get("cost_correction") \
            if isinstance(value, dict) else None
        with self._cond:
            self._tenant_weights = dict(weights or {})
            self._wfq.set_weights(self._tenant_weights)
            self._cost_correction = dict(correction or {})
            self._cond.notify_all()

    def _update_replicas(self, table: Any) -> None:
        from ..core.api import ActorHandle

        table = table or []
        with self._cond:
            fresh = {}
            for entry in table:
                rid = entry["replica_id"]
                existing = self._replicas.get(rid)
                if existing is not None:
                    fresh[rid] = existing
                else:
                    fresh[rid] = {
                        "actor": ActorHandle(bytes.fromhex(entry["actor_id"])),
                        "max_ongoing": entry["max_ongoing"],
                    }
            self._replicas = fresh
            self._inflight = {rid: self._inflight.get(rid, 0) for rid in fresh}
            self._purge_affinity_locked()
            self._cond.notify_all()

    def _purge_affinity_locked(self) -> None:
        """Drop affinity entries pointing at replicas no longer in the
        table: a dead replica's KV died with it, so its groups must
        cold-prefill wherever they land next — never wait for the corpse."""
        for g, rid in list(self._group_affinity.items()):
            if rid not in self._replicas:
                del self._group_affinity[g]
        for m, rid in list(self._model_affinity.items()):
            if rid not in self._replicas:
                del self._model_affinity[m]
        for rid in list(self._circuit):
            if rid not in self._replicas:
                del self._circuit[rid]

    def _affinity_pick(self, prefix_group: str, candidates: list[str],
                       cfg, deployment: str,
                       spill_out: dict | None = None) -> str | None:
        """Prefix-group affinity with load-aware spill. A group's affine
        replica is used while its in-flight load is within
        ``serve_affinity_spill_margin`` of the coolest candidate;
        otherwise the request spills to pow-2 choice and the group
        REMAPS to the spill target. On a spill whose old replica is
        still ALIVE, ``spill_out["migrate_from"]`` records it so the
        spill target can MIGRATE the group's hot KV pages instead of
        cold-prefilling them (PR-10 residue b closed)."""
        def note(kind: str) -> None:
            self.affinity_stats[kind] += 1
            try:
                _serve_metrics()[f"affinity_{kind}"].inc(
                    tags={"deployment": deployment})
            except Exception:
                pass

        affine = self._group_affinity.get(prefix_group)
        if affine is None:
            note("new_groups")
            return None
        if affine not in candidates:
            # Saturated or dead: dead replicas were purged already, a
            # saturated one counts as a spill (never queue behind it).
            if affine in self._replicas:
                note("spills")
                if spill_out is not None:
                    spill_out["migrate_from"] = affine
            else:
                self._group_affinity.pop(prefix_group, None)
                note("misses")
            return None
        coolest = min(self._inflight.get(rid, 0) for rid in candidates)
        if (self._inflight.get(affine, 0) - coolest
                > cfg.serve_affinity_spill_margin):
            note("spills")
            if spill_out is not None:
                spill_out["migrate_from"] = affine
            return None
        note("hits")
        return affine

    def _note_affinity(self, prefix_group: str, pick: str, cfg,
                       deployment: str) -> None:
        self._group_affinity[prefix_group] = pick
        self._group_affinity.move_to_end(prefix_group)
        while len(self._group_affinity) > max(1, cfg.serve_affinity_map_size):
            self._group_affinity.popitem(last=False)
        stats = self.affinity_stats
        looked = stats["hits"] + stats["misses"] + stats["spills"]
        if looked:
            try:
                _serve_metrics()["affinity_hit_rate"].set(
                    stats["hits"] / looked, tags={"deployment": deployment})
            except Exception:
                pass

    # ------------------------------------------------------ overload hooks
    def _candidates_locked(self, cfg) -> tuple[list[str], int]:
        """Replicas eligible for a new request: below their max_ongoing
        cap and not circuit-blocked. An open circuit past its cooldown
        flips to half_open, where the replica admits ONE probe request at
        a time (eligible only while idle). Returns (candidates,
        circuit_blocked_count)."""
        import time

        now = time.monotonic()
        out: list[str] = []
        blocked = 0
        for rid, r in self._replicas.items():
            st = self._circuit.get(rid)
            if st is not None and st["state"] == "open":
                if now - st["opened_at"] >= \
                        cfg.serve_circuit_breaker_cooldown_s:
                    st["state"] = "half_open"
                else:
                    blocked += 1
                    continue
            if st is not None and st["state"] == "half_open" \
                    and self._inflight.get(rid, 0) > 0:
                blocked += 1  # probe already in flight
                continue
            if self._inflight.get(rid, 0) < r["max_ongoing"]:
                out.append(rid)
        return out, blocked

    def note_request_failure(self, replica_id: str,
                             timeout: bool = False) -> None:
        """A handle to ``replica_id`` failed. Consecutive TIMEOUTS trip
        the circuit breaker (``serve_circuit_breaker_failures``); a
        failed half-open probe re-opens immediately."""
        if not timeout:
            return
        from ..core.config import get_config

        import time

        n = get_config().serve_circuit_breaker_failures
        if not n:
            return
        deployment = self._key.rsplit("::", 1)[-1]
        with self._cond:
            if replica_id not in self._replicas:
                return
            st = self._circuit.setdefault(
                replica_id, {"state": "closed", "failures": 0,
                             "opened_at": 0.0})
            st["failures"] += 1
            if st["state"] == "half_open" or st["failures"] >= n:
                if st["state"] != "open":
                    self.overload_stats["circuit_opens"] += 1
                    try:
                        _serve_metrics()["circuit_open"].inc(
                            tags={"deployment": deployment})
                    except Exception:
                        pass
                st["state"] = "open"
                st["opened_at"] = time.monotonic()
                st["failures"] = 0
            self._cond.notify_all()

    def note_request_success(self, replica_id: str) -> None:
        """A handle to ``replica_id`` completed cleanly: reset its
        failure streak; a successful half-open probe closes the circuit
        and restores the replica to full routing."""
        with self._cond:
            st = self._circuit.get(replica_id)
            if st is None:
                return
            if st["state"] != "closed" or st["failures"]:
                st["state"] = "closed"
                st["failures"] = 0
                self._cond.notify_all()

    def circuit_state(self, replica_id: str) -> str:
        with self._cond:
            st = self._circuit.get(replica_id)
            return st["state"] if st is not None else "closed"

    def _service_rate_locked(self, window_s: float = 30.0) -> float:
        """Observed request completions/sec across this router's replicas
        over the trailing window (0.0 = nothing completed yet)."""
        import time

        now = time.monotonic()
        while self._completions and now - self._completions[0] > window_s:
            self._completions.popleft()
        if not self._completions:
            return 0.0
        return len(self._completions) / max(1e-3, now - self._completions[0])

    def _retry_after_locked(self) -> int:
        """Retry-After for a shed request: the backlog ahead of it (every
        waiter + everything in flight) divided by the observed service
        rate, clamped to [1, 60] seconds."""
        import math

        rate = self._service_rate_locked()
        backlog = len(self._waiters) + sum(self._inflight.values()) + 1
        if rate <= 0.0:
            return 1
        return max(1, min(60, int(math.ceil(backlog / rate))))

    def retry_after_hint(self) -> int:
        with self._cond:
            return self._retry_after_locked()

    def _note_shed_locked(self, deployment: str, reason: str,
                          tenant: str = "default") -> None:
        shed = self.overload_stats["shed"]
        shed[reason] = shed.get(reason, 0) + 1
        by_tenant = self.overload_stats["shed_by_tenant"]
        by_tenant[tenant] = by_tenant.get(tenant, 0) + 1
        try:
            _serve_metrics()["shed"].inc(
                tags={"deployment": deployment, "reason": reason,
                      "tenant": tenant})
        except Exception:
            pass

    def overload_snapshot(self) -> dict:
        """Shed/deadline/circuit counters + live circuit states, for
        ``serve.status()`` / ``cli serve status``."""
        with self._cond:
            return {
                "shed": dict(self.overload_stats["shed"]),
                "shed_by_tenant":
                    dict(self.overload_stats["shed_by_tenant"]),
                "deadline_expired_queued":
                    self.overload_stats["deadline_expired_queued"],
                "circuit_opens": self.overload_stats["circuit_opens"],
                "circuit": {rid: st["state"]
                            for rid, st in self._circuit.items()
                            if st["state"] != "closed"},
                "queued": len(self._waiters),
            }

    def assign_replica(self, timeout: float | None = None,
                       model_id: str = "",
                       prefix_group: str = "",
                       spill_out: dict | None = None,
                       deadline: float | None = None,
                       cost: float = 1.0) -> tuple[str, Any]:
        """Power-of-two choice among replicas below their cap; blocks while
        every replica is saturated (backpressure) — but only up to the
        ``serve_max_queued_requests`` bound: over it the request is SHED
        with a fast ``RequestShed`` (503 + Retry-After) instead of
        joining a collapse, preferring (``serve_shed_policy="cost"``) to
        shed requests with the largest cold suffix — a request whose
        prefix group's KV is resident is cheap and may preempt a cold
        waiter's queue slot. A wall-clock ``deadline`` caps the wait:
        expiry raises ``DeadlineExceeded`` without ever touching a
        replica. Replicas tripped by the circuit breaker are excluded
        until their half-open probe succeeds. With a multiplexed
        ``model_id``, replicas that served that model recently are
        preferred (cache affinity — reference multiplex-aware routing).
        With a ``prefix_group`` key, requests stick to the replica whose
        engine already holds the group's KV prefix, with load-aware
        spill (``_affinity_pick``). ``spill_out`` (out-param) reports a
        spill's still-alive previous replica as ``{"migrate_from",
        "actor_id"}`` so the caller can ship a KV-migration source with
        the request."""
        import time

        from ..core.config import get_config
        from ..llm.tenancy import tenant_of

        cfg = get_config()
        if timeout is None:
            timeout = cfg.serve_router_assign_timeout_s
        wait_deadline = time.monotonic() + timeout
        deployment = self._key.rsplit("::", 1)[-1]
        tenant = tenant_of(model_id)
        entry: dict | None = None
        with self._cond:
            try:
                while True:
                    candidates, circuit_blocked = \
                        self._candidates_locked(cfg)
                    queued = any(not e.get("shed") for e in self._waiters
                                 if e is not entry)
                    if candidates and (self._wfq_head_locked(entry)
                                       if entry is not None
                                       else not queued):
                        # Weighted fair queueing: a QUEUED request
                        # proceeds only while it holds the minimum
                        # virtual finish time among waiters, so under
                        # saturation admitted throughput follows tenant
                        # weights instead of arrival order — and a fresh
                        # arrival never barges past the wait queue (it
                        # joins it below instead).
                        if entry is not None:
                            self._wfq.complete(entry["ticket"])
                            entry["ticket"] = None
                            self._cond.notify_all()
                        return self._pick_locked(
                            candidates, cfg, deployment, model_id,
                            prefix_group, spill_out)
                    if deadline is not None and time.time() >= deadline:
                        self.overload_stats["deadline_expired_queued"] += 1
                        try:
                            _serve_metrics()["deadline_expired"].inc(
                                tags={"deployment": deployment,
                                      "where": "queued"})
                        except Exception:
                            pass
                        raise DeadlineExceeded(
                            f"request deadline expired before a replica "
                            f"slot freed for {self._key}")
                    if self._replicas and circuit_blocked and \
                            circuit_blocked >= len(self._replicas):
                        # Every replica's circuit is open (and still
                        # cooling): fail fast, never queue for a corpse.
                        self._note_shed_locked(deployment, "circuit_open",
                                               tenant)
                        raise RequestShed(
                            f"all {len(self._replicas)} replicas of "
                            f"{self._key} are circuit-open",
                            reason="circuit_open",
                            retry_after=self._retry_after_locked())
                    if entry is None:
                        entry = self._enqueue_waiter_locked(
                            cfg, deployment, prefix_group, tenant,
                            cost=cost)
                    elif entry.get("shed"):
                        self._note_shed_locked(deployment, "preempted",
                                               tenant)
                        raise RequestShed(
                            "queue slot preempted by a cached (cheap) "
                            "request under overload",
                            reason="preempted",
                            retry_after=self._retry_after_locked())
                    remaining = wait_deadline - time.monotonic()
                    if deadline is not None:
                        remaining = min(remaining, deadline - time.time())
                    if remaining <= 0:
                        raise TimeoutError(
                            f"No replica available for {self._key} within "
                            f"{timeout}s ({len(self._replicas)} replicas, "
                            "all saturated)")
                    if not self._replicas:
                        # Empty table: likely a scale-to-zero park — poke
                        # the controller to promote a standby, then keep
                        # waiting for the long-poll table push.
                        self._maybe_wake_locked()
                    self._cond.wait(min(remaining, 1.0))
            finally:
                if entry is not None:
                    if entry.get("ticket") is not None:
                        # Shed/timed out before service: drop the WFQ
                        # stamp without advancing the virtual clock.
                        self._wfq.cancel(entry["ticket"])
                    try:
                        self._waiters.remove(entry)
                    except ValueError:
                        pass

    def _wfq_head_locked(self, entry: dict) -> bool:
        ticket = entry.get("ticket")
        return ticket is None or self._wfq.is_head(ticket)

    def _maybe_wake_locked(self) -> None:
        """Fire-and-forget wake_deployment, at most once a second — the
        RPC is idempotent (sets a flag the next reconcile consumes), so
        rate-limiting only spares the controller queue, not correctness."""
        import time as _time

        now = _time.monotonic()
        if now - self._last_wake_rpc < 1.0:
            return
        self._last_wake_rpc = now
        try:
            app, deployment = self._wake_target
            self._controller.wake_deployment.remote(app, deployment)
        except Exception:
            pass

    def _enqueue_waiter_locked(self, cfg, deployment: str,
                               prefix_group: str,
                               tenant: str = "default",
                               cost: float = 1.0) -> dict:
        """Join the router wait queue, enforcing the bound. A cheap
        request (prefix group resident on a live replica → small cold
        suffix) over the bound preempts the oldest expensive waiter's
        slot under the "cost" policy; failing that, a tenant still UNDER
        its weighted fair share of queue slots preempts the newest
        waiter of the most over-share tenant (tenant-aware shedding: a
        noisy tenant's flood sheds its own waiters, not the quiet
        tenant's). Otherwise the incoming request is shed."""
        bound = cfg.serve_max_queued_requests
        cheap = bool(prefix_group
                     and self._group_affinity.get(prefix_group)
                     in self._replicas)
        live = [e for e in self._waiters if not e.get("shed")]
        if bound and self._replicas and len(live) >= bound:
            victim = None
            if cfg.serve_shed_policy == "cost" and cheap:
                victim = next((e for e in live if not e["cheap"]), None)
            if victim is None:
                victim = self._fair_share_victim_locked(live, tenant, bound)
            if victim is None:
                self._note_shed_locked(deployment, "queue_full", tenant)
                raise RequestShed(
                    f"router queue for {self._key} is full "
                    f"({len(live)} waiting, bound {bound})",
                    reason="queue_full",
                    retry_after=self._retry_after_locked())
            victim["shed"] = True
            if victim.get("ticket") is not None:
                # Unblock the WFQ head check immediately — the victim's
                # own thread only wakes to raise its shed.
                self._wfq.cancel(victim["ticket"])
                victim["ticket"] = None
            self._cond.notify_all()
        # WFQ cost = estimated tokens (prompt + max_tokens heuristic
        # from the proxy), scaled by the tenant's published retire-time
        # correction ratio — NOT a flat 1.0/request, so a tenant issuing
        # few huge requests can't out-consume one issuing many small
        # ones at equal weight.
        cost = max(1e-9, float(cost)) * \
            max(0.01, self._cost_correction.get(tenant, 1.0))
        entry = {"cheap": cheap, "shed": False, "tenant": tenant,
                 "ticket": self._wfq.enqueue(tenant, cost=cost)}
        self._waiters.append(entry)
        return entry

    def _fair_share_victim_locked(self, live: list[dict], tenant: str,
                                  bound: int) -> dict | None:
        """Tenant-aware preemption under a full queue: if the incoming
        tenant holds FEWER queue slots than its weight-proportional fair
        share, the newest waiter of the tenant most OVER its share (never
        the incoming tenant) gives up its slot. With one tenant — or no
        configured weights and balanced queues — this never fires, so
        single-tenant shedding behaves exactly as before."""
        counts: dict[str, int] = {}
        for e in live:
            counts[e.get("tenant", "default")] = \
                counts.get(e.get("tenant", "default"), 0) + 1
        tenants = set(counts) | {tenant}
        total_w = sum(max(1e-6, self._tenant_weights.get(t, 1.0))
                      for t in tenants)
        share = {t: bound * max(1e-6, self._tenant_weights.get(t, 1.0))
                 / total_w for t in tenants}
        if counts.get(tenant, 0) >= share[tenant]:
            return None
        worst, worst_over = None, 0.0
        for t, n in counts.items():
            if t == tenant:
                continue
            over = n - share[t]
            if over > worst_over:
                worst, worst_over = t, over
        if worst is None:
            return None
        for e in reversed(live):                     # newest first
            if e.get("tenant", "default") == worst:
                return e
        return None

    def _pick_locked(self, candidates: list[str], cfg, deployment: str,
                     model_id: str, prefix_group: str,
                     spill_out: dict | None) -> tuple[str, Any]:
        pick = None
        if prefix_group:
            pick = self._affinity_pick(prefix_group, candidates,
                                       cfg, deployment,
                                       spill_out=spill_out)
        if pick is None and model_id:
            affine = self._model_affinity.get(model_id)
            if affine in candidates:
                pick = affine
        if pick is None:
            if len(candidates) == 1:
                pick = candidates[0]
            else:
                a, b = random.sample(candidates, 2)
                pick = a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b
        if model_id:
            self._model_affinity[model_id] = pick
            while len(self._model_affinity) > 1024:
                self._model_affinity.pop(next(iter(self._model_affinity)))
        if prefix_group:
            self._note_affinity(prefix_group, pick, cfg,
                                deployment)
        if spill_out is not None:
            src = spill_out.get("migrate_from")
            if src is None or src == pick \
                    or src not in self._replicas:
                # pow-2 re-picked the affine replica (or it
                # vanished): nothing to migrate.
                spill_out.pop("migrate_from", None)
            else:
                spill_out["actor_id"] = \
                    self._replicas[src]["actor"]._actor_id.hex()
        self._inflight[pick] = self._inflight.get(pick, 0) + 1
        return pick, self._replicas[pick]["actor"]

    def release(self, replica_id: str) -> None:
        import time

        with self._cond:
            if replica_id in self._inflight:
                self._inflight[replica_id] = max(0, self._inflight[replica_id] - 1)
            self._completions.append(time.monotonic())
            while len(self._completions) > 4096:
                self._completions.popleft()
            self._cond.notify_all()

    def remove_replica(self, replica_id: str) -> None:
        """Drop a replica observed dead from the local view immediately —
        the controller's long-poll update confirms it later, but a retry
        assigned in the meantime must not land on the same corpse."""
        with self._cond:
            self._replicas.pop(replica_id, None)
            self._inflight.pop(replica_id, None)
            self._purge_affinity_locked()
            self._cond.notify_all()

    def shutdown(self) -> None:
        self._long_poll.stop()


class DeploymentResponse:
    """Future-like result of handle.remote() (reference DeploymentResponse)."""

    def __init__(self, ref, on_done, on_error=None, retry=None):
        self._ref = ref
        self._on_done = on_done
        self._on_error = on_error
        # Optional resubmit hook: result() invokes it when the replica
        # died mid-request (ActorDiedError) — the request is re-routed to
        # a live replica instead of surfacing the infrastructure failure.
        self._retry = retry
        self._settle_lock = threading.Lock()
        self._settled = False
        worker = global_worker()
        oid = ref.id()

        def _cb(_oid):
            self._settle()

        if not worker.memory_store.add_callback(oid, _cb):
            self._settle()

    def _resolved_to_error(self) -> bool:
        """Did the replica call raise? (Inline error payloads carry the
        error metadata marker in the owner's memory store.)"""
        try:
            from ..core import serialization

            entry = global_worker().memory_store.get_if_exists(self._ref.id())
            return bool(entry is not None and not entry.in_plasma
                        and entry.metadata == serialization.META_ERROR)
        except Exception:
            return False

    def _settle(self) -> None:
        # atomic test-and-set: the store callback and a result() caller can
        # race here, and on_done (router slot release) must run exactly once
        with self._settle_lock:
            if self._settled:
                return
            self._settled = True
        try:
            if self._on_error is not None and self._resolved_to_error():
                self._on_error()
        except Exception:
            pass
        try:
            self._on_done()
        except Exception:
            pass

    def result(self, timeout: float | None = 60.0):
        from ..core.status import ActorDiedError

        try:
            value = ray.get(self._ref, timeout=timeout)
        except ActorDiedError:
            self._settle()
            if self._retry is not None:
                # Replica died under the request: re-route once to a live
                # replica (the dead one is already dropped from the local
                # router view by the retry hook).
                return self._retry().result(timeout)
            raise
        self._settle()
        return value

    @property
    def ref(self):
        return self._ref


class DeploymentStreamingResponse:
    """Iterable over a replica's streamed results (reference
    DeploymentResponseGenerator): wraps the core ObjectRefGenerator;
    the router slot is released when the stream ends or is closed.
    Outcomes feed the router's circuit breaker: a clean end notes
    success, an item timeout notes a (breaker-counted) failure, and a
    replica death purges the corpse from the local view. ``deadline``
    (absolute wall clock) caps each item wait — a stream whose next
    token cannot arrive inside the request deadline fails fast."""

    def __init__(self, gen, on_done, router: "Router | None" = None,
                 replica_id: str = "", deadline: float | None = None):
        self._gen = gen
        self._on_done = on_done
        self._router = router
        self._replica_id = replica_id
        self._deadline = deadline
        self._settle_lock = threading.Lock()
        self._settled = False

    def _settle(self) -> None:
        with self._settle_lock:
            if self._settled:
                return
            self._settled = True
        try:
            self._on_done()
        except Exception:
            pass

    def _item_timeout(self, base: float) -> float:
        if self._deadline is not None:
            import time as _time

            return max(0.05, min(base, self._deadline - _time.time()))
        return base

    def _note_outcome(self, ok: bool, timeout: bool = False,
                      died: bool = False) -> None:
        if self._router is None or not self._replica_id:
            return
        try:
            if died:
                self._router.remove_replica(self._replica_id)
            elif ok:
                self._router.note_request_success(self._replica_id)
            else:
                self._router.note_request_failure(self._replica_id,
                                                  timeout=timeout)
        except Exception:
            pass

    def _classify(self, e: BaseException) -> None:
        from ..core.status import ActorDiedError

        if isinstance(e, ActorDiedError):
            self._note_outcome(False, died=True)
        elif isinstance(e, TimeoutError):
            self._note_outcome(False, timeout=True)

    def __iter__(self):
        return self

    def __next__(self):
        from ..core.config import get_config

        try:
            ref = next(self._gen)
            return ray.get(ref, timeout=self._item_timeout(
                get_config().serve_stream_item_timeout_s))
        except StopIteration:
            self._note_outcome(True)
            self._settle()
            raise
        except BaseException as e:
            self._classify(e)
            self._settle()  # a failed get must still release the slot
            raise

    async def __anext__(self):
        try:
            ref = await self._gen.__anext__()
            entry = global_worker().memory_store.get_if_exists(ref.id())
            if entry is not None and not entry.in_plasma:
                # Just-reported inline item: the get is a dict lookup — run
                # it on the loop rather than burning an executor hop.
                return ray.get(ref, timeout=self._item_timeout(120))
            # Plasma-backed (large) item: the shm fetch + raylet RPC would
            # block the proxy loop and stall every other connection.
            import asyncio

            loop = asyncio.get_running_loop()
            timeout = self._item_timeout(120)
            return await loop.run_in_executor(
                None, lambda: ray.get(ref, timeout=timeout))
        except StopAsyncIteration:
            self._note_outcome(True)
            self._settle()
            raise
        except BaseException as e:
            self._classify(e)
            self._settle()
            raise

    def __aiter__(self):
        return self

    def close(self) -> None:
        """Abandon the stream: cancels the replica-side generator."""
        try:
            self._gen.close()
        finally:
            self._settle()


class DeploymentHandle:
    """Client-side handle to a deployment (reference serve.handle.DeploymentHandle)."""

    def __init__(self, app_name: str, deployment_name: str, method_name: str = "",
                 multiplexed_model_id: str = "", prefix_group: str = "",
                 deadline: float | None = None,
                 request_cost: float = 1.0,
                 _router_holder: dict | None = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._prefix_group = prefix_group
        # Absolute wall-clock request deadline (overload protection):
        # caps the router wait, rides the request to the replica, and
        # bounds engine admission/decode.
        self._deadline = deadline
        # Estimated WFQ cost in tokens (prompt + max_tokens heuristic);
        # 1.0 = unknown (plain per-request fairness, the old behavior).
        self._request_cost = request_cost
        # Shared, mutable: every handle derived from this one (h.method)
        # must reuse ONE router — a router per derived handle would leak a
        # long-poll thread per request.
        self._router_holder = (
            _router_holder if _router_holder is not None
            else {"router": None, "lock": threading.Lock()}
        )

    def _get_router(self) -> Router:
        with self._router_holder["lock"]:
            if self._router_holder["router"] is None:
                self._router_holder["router"] = Router(self.app_name, self.deployment_name)
            return self._router_holder["router"]

    def options(self, method_name: str = "",
                multiplexed_model_id: str = "",
                prefix_group: str = "",
                deadline: float | None = None,
                request_cost: float | None = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name, self.deployment_name,
            method_name or self._method_name,
            multiplexed_model_id or self._multiplexed_model_id,
            prefix_group or self._prefix_group,
            deadline if deadline is not None else self._deadline,
            request_cost if request_cost is not None else self._request_cost,
            _router_holder=self._router_holder,
        )

    def __getattr__(self, item: str) -> "DeploymentHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return self.options(method_name=item)

    def _inject_migrate_from(self, router: Router, metrics: dict,
                             spill_out: dict, kwargs: dict) -> None:
        """Ship the spill's previous (still-alive) replica with the
        request so the target migrates the prefix group's KV pages
        instead of recomputing them (config ``serve_spill_migration``)."""
        src = spill_out.get("migrate_from")
        if not src or "actor_id" not in spill_out:
            return
        from ..core.config import get_config

        if not get_config().serve_spill_migration:
            return
        kwargs[MIGRATE_FROM_KWARG] = {"replica_id": src,
                                      "actor_id": spill_out["actor_id"]}
        router.spill_migrations += 1
        try:
            metrics["spill_migrations"].inc(
                tags={"deployment": self.deployment_name})
        except Exception:
            pass

    def remote(self, *args, _replica_death_retries: int = 1,
               **kwargs) -> DeploymentResponse:
        import time as _time

        from .multiplex import MULTIPLEXED_KWARG

        router = self._get_router()
        metrics = _serve_metrics()
        metrics["requests"].inc(tags={"deployment": self.deployment_name})
        t0 = _time.monotonic()
        spill_out: dict = {}
        replica_id, actor = _assign_traced(
            router, metrics, self.deployment_name, self._multiplexed_model_id,
            self._prefix_group, spill_out=spill_out,
            deadline=self._deadline, cost=self._request_cost)
        self._inject_migrate_from(router, metrics, spill_out, kwargs)
        if self._multiplexed_model_id:
            kwargs[MULTIPLEXED_KWARG] = self._multiplexed_model_id
        if self._deadline is not None:
            kwargs[DEADLINE_KWARG] = self._deadline
        try:
            ref = actor.handle_request.remote(self._method_name, args, kwargs)
        except Exception:
            router.release(replica_id)
            metrics["errors"].inc(tags={"deployment": self.deployment_name})
            raise

        def _done():
            router.release(replica_id)
            router.note_request_success(replica_id)
            metrics["latency"].observe(
                1000 * (_time.monotonic() - t0),
                tags={"deployment": self.deployment_name})

        def _retry():
            # The assigned replica died mid-request: purge it from the
            # local view and re-route (the controller replaces it async).
            router.remove_replica(replica_id)
            return self.remote(
                *args, _replica_death_retries=_replica_death_retries - 1,
                **kwargs)

        return DeploymentResponse(
            ref, on_done=_done,
            on_error=lambda: metrics["errors"].inc(
                tags={"deployment": self.deployment_name}),
            retry=_retry if _replica_death_retries > 0 else None)

    def remote_streaming(self, *args, **kwargs) -> DeploymentStreamingResponse:
        """Invoke through the replica's streaming path: results arrive
        item-by-item while the handler runs (token streaming, SSE)."""
        from .multiplex import MULTIPLEXED_KWARG

        import time as _time

        router = self._get_router()
        metrics = _serve_metrics()
        metrics["requests"].inc(tags={"deployment": self.deployment_name})
        t0 = _time.monotonic()
        spill_out: dict = {}
        replica_id, actor = _assign_traced(
            router, metrics, self.deployment_name, self._multiplexed_model_id,
            self._prefix_group, spill_out=spill_out,
            deadline=self._deadline, cost=self._request_cost)
        self._inject_migrate_from(router, metrics, spill_out, kwargs)
        if self._multiplexed_model_id:
            kwargs[MULTIPLEXED_KWARG] = self._multiplexed_model_id
        if self._deadline is not None:
            kwargs[DEADLINE_KWARG] = self._deadline
        try:
            from ..core.config import get_config

            gen = actor.handle_request_streaming.options(
                num_returns="streaming",
                _generator_backpressure_num_objects=get_config().serve_stream_backpressure_items,
            ).remote(self._method_name, args, kwargs)
        except Exception:
            router.release(replica_id)
            metrics["errors"].inc(tags={"deployment": self.deployment_name})
            raise

        def _done():
            # Latency of a stream = full stream duration (close/exhaust).
            router.release(replica_id)
            metrics["latency"].observe(
                1000 * (_time.monotonic() - t0),
                tags={"deployment": self.deployment_name})

        return DeploymentStreamingResponse(
            gen, on_done=_done, router=router, replica_id=replica_id,
            deadline=self._deadline)

    def __reduce__(self):
        return (DeploymentHandle, (self.app_name, self.deployment_name,
                                   self._method_name,
                                   self._multiplexed_model_id,
                                   self._prefix_group,
                                   self._deadline,
                                   self._request_cost))
