"""Router + DeploymentHandle: request scheduling onto replicas.

Reference: ``python/ray/serve/_private/router.py:321`` and
``replica_scheduler/pow_2_scheduler.py:52`` — the router keeps a local
view of each replica's in-flight count, samples two replicas at random
and picks the less loaded one, skipping replicas at their
``max_ongoing_requests`` cap (backpressure: the caller queues until a
slot frees). Replica membership arrives via long-poll from the
controller, so scale-ups and rolling updates apply without polling.
"""

from __future__ import annotations

import hashlib
import random
import threading
from collections import OrderedDict
from typing import Any

from ..core import api as ray
from ..core.worker import global_worker
from .long_poll import LongPollClient

HANDLE_MARKER = "__serve_handle_marker__"

CONTROLLER_NAME = "SERVE_CONTROLLER"

# Spill migration (KV migration, degenerate single-pool case): when a
# prefix-group request spills off its affine replica, the router ships
# the OLD replica's identity along with the request so the spill target
# can pull the group's hot KV pages instead of cold-prefilling them.
# Travels as a reserved kwarg (popped by the replica before the user
# callable sees it) and surfaces through a thread-local, mirroring the
# multiplexed-model-id plumbing.
MIGRATE_FROM_KWARG = "_serve_migrate_from"

_migration_context = threading.local()


def set_migration_source(src: dict | None) -> None:
    """Install the spill-migration source ({"replica_id", "actor_id"} or
    None) for the current request thread (called by the replica before
    invoking the user callable)."""
    _migration_context.source = src


def get_migration_source() -> dict | None:
    """Inside a request: the replica this request spilled away from —
    the one holding its prefix group's cached KV — or None."""
    return getattr(_migration_context, "source", None)

# Request metrics (reference: serve_num_router_requests /
# serve_deployment_processing_latency_ms in serve/_private/router.py) —
# lazily created so importing serve doesn't start the metrics flusher.
_metrics_lock = threading.Lock()
_metrics: dict = {}


def _serve_metrics():
    with _metrics_lock:
        if not _metrics:
            from ..util.metrics import Counter, Gauge, Histogram

            _metrics["requests"] = Counter(
                "serve_num_requests_total",
                "Requests routed to replicas", tag_keys=("deployment",))
            _metrics["errors"] = Counter(
                "serve_num_errors_total",
                "Requests that raised", tag_keys=("deployment",))
            _metrics["latency"] = Histogram(
                "serve_request_latency_ms",
                "End-to-end handle latency",
                boundaries=(1, 5, 25, 100, 250, 500, 1000, 5000, 30000),
                tag_keys=("deployment",))
            _metrics["queue_wait"] = Histogram(
                "serve_queue_wait_ms",
                "Time a request waits in the router for a replica slot",
                tag_keys=("deployment",))
            _metrics["affinity_hits"] = Counter(
                "serve_affinity_hits_total",
                "Requests routed to their prefix group's affine replica",
                tag_keys=("deployment",))
            _metrics["affinity_misses"] = Counter(
                "serve_affinity_misses_total",
                "Prefix-group requests whose affine replica vanished "
                "(died/removed) — the KV must cold-prefill elsewhere",
                tag_keys=("deployment",))
            _metrics["affinity_new_groups"] = Counter(
                "serve_affinity_new_groups_total",
                "First-seen prefix groups (not an affinity failure; "
                "excluded from the hit rate)", tag_keys=("deployment",))
            _metrics["affinity_spills"] = Counter(
                "serve_affinity_spills_total",
                "Prefix-group requests spilled off an overloaded affine "
                "replica (load-aware spill)", tag_keys=("deployment",))
            _metrics["affinity_hit_rate"] = Gauge(
                "serve_prefix_affinity_hit_rate",
                "Fraction of prefix-group requests that landed on their "
                "affine replica (0-1, since router start)",
                tag_keys=("deployment",))
            _metrics["spill_migrations"] = Counter(
                "serve_spill_migrations",
                "Affinity spills shipped with a migrate-from source: the "
                "spill target pulls the group's hot KV pages from the "
                "previous replica instead of cold-prefilling",
                tag_keys=("deployment",))
        return _metrics


def prefix_group_key(session_id: str = "", text: str = "",
                     n_chars: int | None = None) -> str:
    """Prefix-group key for affinity routing: an explicit session id
    wins; otherwise the hash of the prompt's first ``n_chars`` characters
    — under the byte tokenizer that IS the first token blocks, so
    requests sharing a system prompt land in one group. Empty when
    neither is present (no affinity)."""
    if session_id:
        return f"sess:{session_id}"
    if not text:
        return ""
    if n_chars is None:
        from ..core.config import get_config

        n_chars = get_config().serve_prefix_group_chars
    head = text[:n_chars].encode("utf-8", errors="ignore")
    return "pfx:" + hashlib.sha1(head).hexdigest()[:16]


def _assign_traced(router: "Router", metrics: dict, deployment: str,
                   model_id: str, prefix_group: str = "",
                   spill_out: dict | None = None) -> tuple[str, Any]:
    """Assign a replica, recording the router queue wait as both a
    histogram observation and (inside an active trace) a span."""
    import time as _time

    from ..observability import tracing

    t0w, t0m = _time.time(), _time.monotonic()
    try:
        replica_id, actor = router.assign_replica(
            model_id=model_id, prefix_group=prefix_group,
            spill_out=spill_out)
    finally:
        wait_ms = 1000 * (_time.monotonic() - t0m)
        metrics["queue_wait"].observe(wait_ms, tags={"deployment": deployment})
        ctx = tracing.current()
        if ctx is not None:
            tracing.record_span(tracing.make_span(
                f"router.queue {deployment}", "serve", t0w, _time.time(),
                ctx.trace_id, ctx.span_id,
                attrs={"deployment": deployment}))
    return replica_id, actor


def resolve_handle_markers(obj):
    """Replace deploy-time handle markers with live DeploymentHandles
    (composition: a deployment's init args may reference other
    deployments)."""
    if isinstance(obj, tuple):
        return tuple(resolve_handle_markers(o) for o in obj)
    if isinstance(obj, list):
        return [resolve_handle_markers(o) for o in obj]
    if isinstance(obj, dict):
        if obj.get("t") == HANDLE_MARKER:
            return DeploymentHandle(obj["app"], obj["deployment"])
        return {k: resolve_handle_markers(v) for k, v in obj.items()}
    return obj


class Router:
    """Per-process router for one deployment."""

    def __init__(self, app_name: str, deployment_name: str):
        self._key = f"replicas::{app_name}::{deployment_name}"
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # replica_id -> {"actor": ActorHandle, "max_ongoing": int}
        self._replicas: dict[str, dict] = {}
        self._inflight: dict[str, int] = {}
        # multiplexing cache affinity: model_id -> last replica that served it
        self._model_affinity: dict[str, str] = {}
        # Prefix/session affinity: group key -> replica whose engine holds
        # that group's KV prefix (bounded LRU; load-aware spill keeps a
        # hot replica from queue-blowing on affinity alone).
        self._group_affinity: OrderedDict[str, str] = OrderedDict()
        self.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                               "new_groups": 0}
        # Spills that shipped a migrate-from source with the request
        # (the KV moved instead of being recomputed).
        self.spill_migrations = 0
        controller = ray.get_actor(CONTROLLER_NAME)
        self._long_poll = LongPollClient(controller, {self._key: self._update_replicas})
        # prime with the current table so the first request needn't wait a
        # full poll round-trip
        try:
            snap = ray.get(controller.get_snapshot.remote(self._key), timeout=30)
            if snap is not None:
                self._update_replicas(snap)
        except Exception:
            pass

    def _update_replicas(self, table: Any) -> None:
        from ..core.api import ActorHandle

        table = table or []
        with self._cond:
            fresh = {}
            for entry in table:
                rid = entry["replica_id"]
                existing = self._replicas.get(rid)
                if existing is not None:
                    fresh[rid] = existing
                else:
                    fresh[rid] = {
                        "actor": ActorHandle(bytes.fromhex(entry["actor_id"])),
                        "max_ongoing": entry["max_ongoing"],
                    }
            self._replicas = fresh
            self._inflight = {rid: self._inflight.get(rid, 0) for rid in fresh}
            self._purge_affinity_locked()
            self._cond.notify_all()

    def _purge_affinity_locked(self) -> None:
        """Drop affinity entries pointing at replicas no longer in the
        table: a dead replica's KV died with it, so its groups must
        cold-prefill wherever they land next — never wait for the corpse."""
        for g, rid in list(self._group_affinity.items()):
            if rid not in self._replicas:
                del self._group_affinity[g]
        for m, rid in list(self._model_affinity.items()):
            if rid not in self._replicas:
                del self._model_affinity[m]

    def _affinity_pick(self, prefix_group: str, candidates: list[str],
                       cfg, deployment: str,
                       spill_out: dict | None = None) -> str | None:
        """Prefix-group affinity with load-aware spill. A group's affine
        replica is used while its in-flight load is within
        ``serve_affinity_spill_margin`` of the coolest candidate;
        otherwise the request spills to pow-2 choice and the group
        REMAPS to the spill target. On a spill whose old replica is
        still ALIVE, ``spill_out["migrate_from"]`` records it so the
        spill target can MIGRATE the group's hot KV pages instead of
        cold-prefilling them (PR-10 residue b closed)."""
        def note(kind: str) -> None:
            self.affinity_stats[kind] += 1
            try:
                _serve_metrics()[f"affinity_{kind}"].inc(
                    tags={"deployment": deployment})
            except Exception:
                pass

        affine = self._group_affinity.get(prefix_group)
        if affine is None:
            note("new_groups")
            return None
        if affine not in candidates:
            # Saturated or dead: dead replicas were purged already, a
            # saturated one counts as a spill (never queue behind it).
            if affine in self._replicas:
                note("spills")
                if spill_out is not None:
                    spill_out["migrate_from"] = affine
            else:
                self._group_affinity.pop(prefix_group, None)
                note("misses")
            return None
        coolest = min(self._inflight.get(rid, 0) for rid in candidates)
        if (self._inflight.get(affine, 0) - coolest
                > cfg.serve_affinity_spill_margin):
            note("spills")
            if spill_out is not None:
                spill_out["migrate_from"] = affine
            return None
        note("hits")
        return affine

    def _note_affinity(self, prefix_group: str, pick: str, cfg,
                       deployment: str) -> None:
        self._group_affinity[prefix_group] = pick
        self._group_affinity.move_to_end(prefix_group)
        while len(self._group_affinity) > max(1, cfg.serve_affinity_map_size):
            self._group_affinity.popitem(last=False)
        stats = self.affinity_stats
        looked = stats["hits"] + stats["misses"] + stats["spills"]
        if looked:
            try:
                _serve_metrics()["affinity_hit_rate"].set(
                    stats["hits"] / looked, tags={"deployment": deployment})
            except Exception:
                pass

    def assign_replica(self, timeout: float | None = None,
                       model_id: str = "",
                       prefix_group: str = "",
                       spill_out: dict | None = None) -> tuple[str, Any]:
        """Power-of-two choice among replicas below their cap; blocks while
        every replica is saturated (backpressure). With a multiplexed
        ``model_id``, replicas that served that model recently are
        preferred (cache affinity — reference multiplex-aware routing).
        With a ``prefix_group`` key, requests stick to the replica whose
        engine already holds the group's KV prefix, with load-aware
        spill (``_affinity_pick``). ``spill_out`` (out-param) reports a
        spill's still-alive previous replica as ``{"migrate_from",
        "actor_id"}`` so the caller can ship a KV-migration source with
        the request."""
        import time

        from ..core.config import get_config

        cfg = get_config()
        if timeout is None:
            timeout = cfg.serve_router_assign_timeout_s
        deadline = time.monotonic() + timeout
        deployment = self._key.rsplit("::", 1)[-1]
        with self._cond:
            while True:
                candidates = [
                    rid for rid, r in self._replicas.items()
                    if self._inflight.get(rid, 0) < r["max_ongoing"]
                ]
                if candidates:
                    pick = None
                    if prefix_group:
                        pick = self._affinity_pick(prefix_group, candidates,
                                                   cfg, deployment,
                                                   spill_out=spill_out)
                    if pick is None and model_id:
                        affine = self._model_affinity.get(model_id)
                        if affine in candidates:
                            pick = affine
                    if pick is None:
                        if len(candidates) == 1:
                            pick = candidates[0]
                        else:
                            a, b = random.sample(candidates, 2)
                            pick = a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b
                    if model_id:
                        self._model_affinity[model_id] = pick
                        while len(self._model_affinity) > 1024:
                            self._model_affinity.pop(next(iter(self._model_affinity)))
                    if prefix_group:
                        self._note_affinity(prefix_group, pick, cfg,
                                            deployment)
                    if spill_out is not None:
                        src = spill_out.get("migrate_from")
                        if src is None or src == pick \
                                or src not in self._replicas:
                            # pow-2 re-picked the affine replica (or it
                            # vanished): nothing to migrate.
                            spill_out.pop("migrate_from", None)
                        else:
                            spill_out["actor_id"] = \
                                self._replicas[src]["actor"]._actor_id.hex()
                    self._inflight[pick] = self._inflight.get(pick, 0) + 1
                    return pick, self._replicas[pick]["actor"]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"No replica available for {self._key} within {timeout}s "
                        f"({len(self._replicas)} replicas, all saturated)"
                    )
                self._cond.wait(min(remaining, 1.0))

    def release(self, replica_id: str) -> None:
        with self._cond:
            if replica_id in self._inflight:
                self._inflight[replica_id] = max(0, self._inflight[replica_id] - 1)
            self._cond.notify_all()

    def remove_replica(self, replica_id: str) -> None:
        """Drop a replica observed dead from the local view immediately —
        the controller's long-poll update confirms it later, but a retry
        assigned in the meantime must not land on the same corpse."""
        with self._cond:
            self._replicas.pop(replica_id, None)
            self._inflight.pop(replica_id, None)
            self._purge_affinity_locked()
            self._cond.notify_all()

    def shutdown(self) -> None:
        self._long_poll.stop()


class DeploymentResponse:
    """Future-like result of handle.remote() (reference DeploymentResponse)."""

    def __init__(self, ref, on_done, on_error=None, retry=None):
        self._ref = ref
        self._on_done = on_done
        self._on_error = on_error
        # Optional resubmit hook: result() invokes it when the replica
        # died mid-request (ActorDiedError) — the request is re-routed to
        # a live replica instead of surfacing the infrastructure failure.
        self._retry = retry
        self._settle_lock = threading.Lock()
        self._settled = False
        worker = global_worker()
        oid = ref.id()

        def _cb(_oid):
            self._settle()

        if not worker.memory_store.add_callback(oid, _cb):
            self._settle()

    def _resolved_to_error(self) -> bool:
        """Did the replica call raise? (Inline error payloads carry the
        error metadata marker in the owner's memory store.)"""
        try:
            from ..core import serialization

            entry = global_worker().memory_store.get_if_exists(self._ref.id())
            return bool(entry is not None and not entry.in_plasma
                        and entry.metadata == serialization.META_ERROR)
        except Exception:
            return False

    def _settle(self) -> None:
        # atomic test-and-set: the store callback and a result() caller can
        # race here, and on_done (router slot release) must run exactly once
        with self._settle_lock:
            if self._settled:
                return
            self._settled = True
        try:
            if self._on_error is not None and self._resolved_to_error():
                self._on_error()
        except Exception:
            pass
        try:
            self._on_done()
        except Exception:
            pass

    def result(self, timeout: float | None = 60.0):
        from ..core.status import ActorDiedError

        try:
            value = ray.get(self._ref, timeout=timeout)
        except ActorDiedError:
            self._settle()
            if self._retry is not None:
                # Replica died under the request: re-route once to a live
                # replica (the dead one is already dropped from the local
                # router view by the retry hook).
                return self._retry().result(timeout)
            raise
        self._settle()
        return value

    @property
    def ref(self):
        return self._ref


class DeploymentStreamingResponse:
    """Iterable over a replica's streamed results (reference
    DeploymentResponseGenerator): wraps the core ObjectRefGenerator;
    the router slot is released when the stream ends or is closed."""

    def __init__(self, gen, on_done):
        self._gen = gen
        self._on_done = on_done
        self._settle_lock = threading.Lock()
        self._settled = False

    def _settle(self) -> None:
        with self._settle_lock:
            if self._settled:
                return
            self._settled = True
        try:
            self._on_done()
        except Exception:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        from ..core.config import get_config

        try:
            ref = next(self._gen)
            return ray.get(ref, timeout=get_config().serve_stream_item_timeout_s)
        except StopIteration:
            self._settle()
            raise
        except BaseException:
            self._settle()  # a failed get must still release the slot
            raise

    async def __anext__(self):
        try:
            ref = await self._gen.__anext__()
            entry = global_worker().memory_store.get_if_exists(ref.id())
            if entry is not None and not entry.in_plasma:
                # Just-reported inline item: the get is a dict lookup — run
                # it on the loop rather than burning an executor hop.
                return ray.get(ref, timeout=120)
            # Plasma-backed (large) item: the shm fetch + raylet RPC would
            # block the proxy loop and stall every other connection.
            import asyncio

            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, lambda: ray.get(ref, timeout=120))
        except StopAsyncIteration:
            self._settle()
            raise
        except BaseException:
            self._settle()
            raise

    def __aiter__(self):
        return self

    def close(self) -> None:
        """Abandon the stream: cancels the replica-side generator."""
        try:
            self._gen.close()
        finally:
            self._settle()


class DeploymentHandle:
    """Client-side handle to a deployment (reference serve.handle.DeploymentHandle)."""

    def __init__(self, app_name: str, deployment_name: str, method_name: str = "",
                 multiplexed_model_id: str = "", prefix_group: str = "",
                 _router_holder: dict | None = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._prefix_group = prefix_group
        # Shared, mutable: every handle derived from this one (h.method)
        # must reuse ONE router — a router per derived handle would leak a
        # long-poll thread per request.
        self._router_holder = (
            _router_holder if _router_holder is not None
            else {"router": None, "lock": threading.Lock()}
        )

    def _get_router(self) -> Router:
        with self._router_holder["lock"]:
            if self._router_holder["router"] is None:
                self._router_holder["router"] = Router(self.app_name, self.deployment_name)
            return self._router_holder["router"]

    def options(self, method_name: str = "",
                multiplexed_model_id: str = "",
                prefix_group: str = "") -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name, self.deployment_name,
            method_name or self._method_name,
            multiplexed_model_id or self._multiplexed_model_id,
            prefix_group or self._prefix_group,
            _router_holder=self._router_holder,
        )

    def __getattr__(self, item: str) -> "DeploymentHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return self.options(method_name=item)

    def _inject_migrate_from(self, router: Router, metrics: dict,
                             spill_out: dict, kwargs: dict) -> None:
        """Ship the spill's previous (still-alive) replica with the
        request so the target migrates the prefix group's KV pages
        instead of recomputing them (config ``serve_spill_migration``)."""
        src = spill_out.get("migrate_from")
        if not src or "actor_id" not in spill_out:
            return
        from ..core.config import get_config

        if not get_config().serve_spill_migration:
            return
        kwargs[MIGRATE_FROM_KWARG] = {"replica_id": src,
                                      "actor_id": spill_out["actor_id"]}
        router.spill_migrations += 1
        try:
            metrics["spill_migrations"].inc(
                tags={"deployment": self.deployment_name})
        except Exception:
            pass

    def remote(self, *args, _replica_death_retries: int = 1,
               **kwargs) -> DeploymentResponse:
        import time as _time

        from .multiplex import MULTIPLEXED_KWARG

        router = self._get_router()
        metrics = _serve_metrics()
        metrics["requests"].inc(tags={"deployment": self.deployment_name})
        t0 = _time.monotonic()
        spill_out: dict = {}
        replica_id, actor = _assign_traced(
            router, metrics, self.deployment_name, self._multiplexed_model_id,
            self._prefix_group, spill_out=spill_out)
        self._inject_migrate_from(router, metrics, spill_out, kwargs)
        if self._multiplexed_model_id:
            kwargs[MULTIPLEXED_KWARG] = self._multiplexed_model_id
        try:
            ref = actor.handle_request.remote(self._method_name, args, kwargs)
        except Exception:
            router.release(replica_id)
            metrics["errors"].inc(tags={"deployment": self.deployment_name})
            raise

        def _done():
            router.release(replica_id)
            metrics["latency"].observe(
                1000 * (_time.monotonic() - t0),
                tags={"deployment": self.deployment_name})

        def _retry():
            # The assigned replica died mid-request: purge it from the
            # local view and re-route (the controller replaces it async).
            router.remove_replica(replica_id)
            return self.remote(
                *args, _replica_death_retries=_replica_death_retries - 1,
                **kwargs)

        return DeploymentResponse(
            ref, on_done=_done,
            on_error=lambda: metrics["errors"].inc(
                tags={"deployment": self.deployment_name}),
            retry=_retry if _replica_death_retries > 0 else None)

    def remote_streaming(self, *args, **kwargs) -> DeploymentStreamingResponse:
        """Invoke through the replica's streaming path: results arrive
        item-by-item while the handler runs (token streaming, SSE)."""
        from .multiplex import MULTIPLEXED_KWARG

        import time as _time

        router = self._get_router()
        metrics = _serve_metrics()
        metrics["requests"].inc(tags={"deployment": self.deployment_name})
        t0 = _time.monotonic()
        spill_out: dict = {}
        replica_id, actor = _assign_traced(
            router, metrics, self.deployment_name, self._multiplexed_model_id,
            self._prefix_group, spill_out=spill_out)
        self._inject_migrate_from(router, metrics, spill_out, kwargs)
        if self._multiplexed_model_id:
            kwargs[MULTIPLEXED_KWARG] = self._multiplexed_model_id
        try:
            from ..core.config import get_config

            gen = actor.handle_request_streaming.options(
                num_returns="streaming",
                _generator_backpressure_num_objects=get_config().serve_stream_backpressure_items,
            ).remote(self._method_name, args, kwargs)
        except Exception:
            router.release(replica_id)
            metrics["errors"].inc(tags={"deployment": self.deployment_name})
            raise

        def _done():
            # Latency of a stream = full stream duration (close/exhaust).
            router.release(replica_id)
            metrics["latency"].observe(
                1000 * (_time.monotonic() - t0),
                tags={"deployment": self.deployment_name})

        return DeploymentStreamingResponse(gen, on_done=_done)

    def __reduce__(self):
        return (DeploymentHandle, (self.app_name, self.deployment_name,
                                   self._method_name,
                                   self._multiplexed_model_id,
                                   self._prefix_group))
