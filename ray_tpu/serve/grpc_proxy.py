"""gRPC ingress proxy.

Equivalent of the reference's gRPC proxy
(``python/ray/serve/_private/proxy.py:534``): a generic gRPC server that
routes ``/<app>/<method>`` unary calls onto deployment replicas through
the same power-of-two router as HTTP. Payloads are cloudpickled
request/response values (the reference routes user-defined protobufs; the
generic-bytes contract here keeps the surface protoc-free while the
transport, routing, and backpressure are the real thing).

Client usage::

    channel = grpc.insecure_channel(address)
    call = channel.unary_unary("/my_app/__call__")
    result = cloudpickle.loads(call(cloudpickle.dumps((args, kwargs))))
"""

from __future__ import annotations

import threading
from typing import Any

import cloudpickle

from ..core import api as ray
from .long_poll import LongPollClient
from .router import CONTROLLER_NAME, DeploymentHandle


class _GenericHandler:
    """grpc.GenericRpcHandler routing every unary method by path."""

    def __init__(self, proxy: "GrpcProxyActor"):
        self._proxy = proxy

    def service(self, handler_call_details):
        import grpc

        method = handler_call_details.method  # "/app/method"

        def unary(request: bytes, context) -> bytes:
            try:
                return self._proxy.dispatch(method, request)
            except Exception as e:
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


class GrpcProxyActor:
    """Per-cluster gRPC ingress (runs as a Serve-internal actor, like the
    HTTP proxy)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        import grpc
        from concurrent import futures

        self._routes: dict[str, tuple[str, str]] = {}  # app -> (app, ingress)
        self._handles: dict[str, DeploymentHandle] = {}
        controller = ray.get_actor(CONTROLLER_NAME)
        self._long_poll = LongPollClient(controller, {"routes": self._update_routes})
        try:
            snap = ray.get(controller.get_snapshot.remote("routes"), timeout=30)
            if snap:
                self._update_routes(snap)
        except Exception:
            pass
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=32),
            options=[("grpc.so_reuseport", 0)],
        )
        self._server.add_generic_rpc_handlers((_GenericHandler(self),))
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        self._address = f"127.0.0.1:{bound}"

    def _update_routes(self, table: Any) -> None:
        self._routes = {e["app"]: (e["app"], e["deployment"]) for e in (table or [])}

    def address(self) -> str:
        return self._address

    def ready(self) -> bool:
        return True

    def dispatch(self, method: str, request: bytes) -> bytes:
        parts = method.strip("/").split("/")
        if len(parts) != 2:
            raise ValueError(f"gRPC method must be /app/method, got {method!r}")
        app, target_method = parts
        key = self._routes.get(app)
        if key is None:
            raise KeyError(f"no Serve application named {app!r}")
        handle = self._handles.get(app)
        if handle is None:
            handle = self._handles[app] = DeploymentHandle(*key)
        args, kwargs = cloudpickle.loads(request) if request else ((), {})
        h = handle.options(method_name="" if target_method == "__call__" else target_method)
        result = h.remote(*args, **kwargs).result(timeout=120)
        return cloudpickle.dumps(result)

    def shutdown(self) -> None:
        self._server.stop(grace=0.5)
        self._long_poll.stop()


_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"
_lock = threading.Lock()


def start_grpc(port: int = 0) -> str:
    """Start (or return) the cluster's gRPC ingress; returns its address
    (reference: serve.start(grpc_options=...))."""
    with _lock:
        try:
            proxy = ray.get_actor(_GRPC_PROXY_NAME)
        except ValueError:
            cls = ray.remote(GrpcProxyActor)
            try:
                proxy = cls.options(name=_GRPC_PROXY_NAME, lifetime="detached",
                                    num_cpus=0, max_concurrency=64).remote("0.0.0.0", port)
            except Exception:
                proxy = ray.get_actor(_GRPC_PROXY_NAME)  # lost the name race
        return ray.get(proxy.address.remote(), timeout=60)
