"""Replica actor: hosts one copy of a deployment's user callable.

Reference: ``python/ray/serve/_private/replica.py`` (UserCallableWrapper).
The replica exposes readiness/health/queue-length probes for the
controller and ``handle_request`` for routers; ongoing-request counts feed
both the router's power-of-two choice and queue-based autoscaling.
"""

from __future__ import annotations

import threading
from typing import Any

import cloudpickle


class Request:
    """Minimal HTTP request view handed to ingress deployments
    (reference passes a starlette Request)."""

    def __init__(self, method: str = "GET", path: str = "/", query: dict | None = None,
                 headers: dict | None = None, body: bytes = b""):
        self.method = method
        self.path = path
        self.query_params = query or {}
        self.headers = headers or {}
        self.body = body

    def json(self):
        import json

        return json.loads(self.body or b"null")

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query_params, self.headers, self.body))


# Identity of the replica hosted by THIS worker process (one replica actor
# per worker), set before the user callable is constructed so deployment
# code — e.g. the LLM deployment's TTFT histogram — can tag its metrics
# with the serve deployment it runs in (reference
# serve.get_replica_context()).
_REPLICA_CONTEXT: dict | None = None


def get_replica_context() -> dict | None:
    return _REPLICA_CONTEXT


class ReplicaActor:
    """One deployment replica. Created by the controller with the pickled
    user class so replicas never re-import application modules."""

    def __init__(self, serialized_callable: bytes, init_args: tuple, init_kwargs: dict,
                 user_config: Any = None, deployment_name: str = "", app_name: str = "",
                 replica_id: str = ""):
        from .router import resolve_handle_markers

        global _REPLICA_CONTEXT
        _REPLICA_CONTEXT = {"app": app_name, "deployment": deployment_name,
                            "replica_id": replica_id}
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._deployment_name = deployment_name
        self._app_name = app_name
        self._replica_id = replica_id
        try:
            func_or_class = cloudpickle.loads(serialized_callable)
            init_args = resolve_handle_markers(init_args)
            init_kwargs = resolve_handle_markers(init_kwargs)
            if isinstance(func_or_class, type):
                self._callable = func_or_class(*init_args, **init_kwargs)
            else:
                self._callable = func_or_class  # plain function deployment
            if user_config is not None:
                self.reconfigure(user_config)
        except Exception as e:
            # Publish the constructor's full traceback on the error-info
            # channel from INSIDE the replica process, then re-raise so the
            # actor-creation failure path still runs — the controller's
            # "failed to start" must never be cause-less again.
            import traceback

            from ..diagnostics.errors import publish_error_to_driver

            publish_error_to_driver(
                "replica_start_failure",
                f"replica of {app_name}#{deployment_name} failed in "
                f"__init__: {type(e).__name__}: {e}",
                source="serve_replica", traceback=traceback.format_exc(),
                extra={"app": app_name, "deployment": deployment_name})
            raise

    def ready(self) -> bool:
        return True

    def check_health(self) -> bool:
        probe = getattr(self._callable, "check_health", None)
        if probe is not None:
            probe()
        return True

    def get_queue_len(self) -> int:
        with self._lock:
            return self._ongoing

    def stats(self) -> dict:
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total}

    def latency_snapshot(self) -> list[dict]:
        """Cumulative latency histograms recorded IN this replica process
        (``serve_ttft_ms`` from an engine-hosting callable, plus any
        ``serve_queue_wait_ms`` observed locally), for the controller's
        latency-SLO autoscaler — pulled via the probe path so scaling
        never waits on the ~5 s GCS metrics flush. Callables exposing
        ``prefix_residency()`` (the LLM deployment) piggyback a
        ``serve_prefix_residency`` row — per-group KV residency counts
        the controller folds into the app status's affinity hit rates."""
        from ..util.metrics import snapshot_all

        names = ("serve_ttft_ms", "serve_queue_wait_ms")
        rows = [
            m for m in snapshot_all()
            if m["name"] in names
            and m.get("tags", {}).get("deployment", "") in (
                "", self._deployment_name)
        ]
        residency = getattr(self._callable, "prefix_residency", None)
        if residency is not None:
            try:
                rows.append({"name": "serve_prefix_residency",
                             **(residency() or {})})
            except Exception:
                pass
        # Overload counters (deadline expiries, engine queue sheds,
        # admission-watermark rejects) piggyback the same probe for the
        # controller's per-deployment status aggregation.
        overload = getattr(self._callable, "overload_stats", None)
        if overload is not None:
            try:
                rows.append({"name": "serve_overload",
                             **(overload() or {})})
            except Exception:
                pass
        # Tenancy rows (per-tenant quotas/TTFT + resident adapters) ride
        # the same probe so serve.status() shows per-tenant state without
        # waiting on the metrics flush.
        tenancy = getattr(self._callable, "tenancy_stats", None)
        if tenancy is not None:
            try:
                rows.append({"name": "serve_tenancy",
                             **(tenancy() or {})})
            except Exception:
                pass
        # Fleet rows (request-idle age + weight residency) feed the
        # controller's scale-to-zero / standby decisions.
        fleet = getattr(self._callable, "fleet_stats", None)
        if fleet is not None:
            try:
                rows.append({"name": "serve_fleet", **(fleet() or {})})
            except Exception:
                pass
        return rows

    # ------------------------------------------------------ fleet lifecycle
    def fleet_demote(self) -> dict:
        """Demote to STANDBY: weights to host RAM, compile cache kept.
        Plain callables have nothing to demote — report unsupported so
        the controller leaves them RUNNING."""
        fn = getattr(self._callable, "fleet_demote", None)
        if fn is None:
            return {"ok": False, "reason": "unsupported"}
        with self._lock:
            if self._ongoing:
                return {"ok": False, "reason": "busy"}
        return fn()

    def fleet_promote(self, weight_address: str | None = None) -> dict:
        """Promote from STANDBY back to serving. Plain callables never
        demoted, so promotion is trivially complete."""
        fn = getattr(self._callable, "fleet_promote", None)
        if fn is None:
            return {"ok": True, "path": "noop"}
        return fn(weight_address)

    def open_weight_stream(self, n_readers: int = 1) -> dict | None:
        """Open a weight broadcast from this replica (the donor side of
        a fan-out promotion). None when the callable can't serve one."""
        fn = getattr(self._callable, "open_weight_stream", None)
        if fn is None:
            return None
        return fn(n_readers)

    def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def _chaos_delay(self) -> None:
        """Chaos injection point: per-replica handle delays (the
        ``replica_delay`` FaultPlan kind) — a deterministic stand-in for
        a replica gone slow, used to exercise the deadline/circuit paths
        under the overload chaos plan."""
        from ..core.rpc import get_chaos

        chaos = get_chaos()
        fn = getattr(chaos, "replica_delay_s", None)
        if fn is None:
            return
        try:
            delay = fn(self._replica_id)
        except Exception:
            return
        if delay > 0:
            import time

            time.sleep(delay)

    def handle_request(self, method_name: str, args: tuple, kwargs: dict):
        from .multiplex import MULTIPLEXED_KWARG, set_multiplexed_model_id
        from .router import (DEADLINE_KWARG, MIGRATE_FROM_KWARG,
                             set_migration_source, set_request_deadline)

        set_multiplexed_model_id(kwargs.pop(MULTIPLEXED_KWARG, ""))
        set_migration_source(kwargs.pop(MIGRATE_FROM_KWARG, None))
        set_request_deadline(kwargs.pop(DEADLINE_KWARG, None))
        self._chaos_delay()
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target = getattr(self._callable, method_name) if method_name else self._callable
            result = target(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args: tuple, kwargs: dict):
        """Streaming request path (invoked with ``num_returns="streaming"``):
        drives the user callable and yields response **wire messages** —
        the reference proxy's ASGI-message stream over a generator task
        (``python/ray/serve/_private/proxy.py:754``):

          {"kind": "full", "data": value}          — non-streaming handler
          {"kind": "start", "content_type": ...}    — streaming handler head
          {"kind": "chunk", "data": bytes}          — one body chunk
          {"kind": "error", "error": str}           — handler raised

        A streaming handler is one whose result is a (sync/async)
        generator; it may yield a leading ``{"__serve_response__": ...}``
        dict to set status/content-type, then str/bytes/dict chunks.
        """
        import inspect
        import json as _json

        from .multiplex import MULTIPLEXED_KWARG, set_multiplexed_model_id
        from .router import (DEADLINE_KWARG, MIGRATE_FROM_KWARG,
                             set_migration_source, set_request_deadline)

        set_multiplexed_model_id(kwargs.pop(MULTIPLEXED_KWARG, ""))
        set_migration_source(kwargs.pop(MIGRATE_FROM_KWARG, None))
        set_request_deadline(kwargs.pop(DEADLINE_KWARG, None))
        self._chaos_delay()
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target = getattr(self._callable, method_name) if method_name else self._callable
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
            if not (inspect.isgenerator(result) or hasattr(result, "__anext__")):
                yield {"kind": "full", "data": result}
                return
            items = _drive(result)
            first = next(items, None)
            head = {"kind": "start", "status": "200 OK", "content_type": "application/octet-stream"}
            if isinstance(first, dict) and first.get("__serve_response__"):
                head["content_type"] = first.get("content_type", head["content_type"])
                head["status"] = first.get("status", head["status"])
                first = next(items, None)
            yield head
            import itertools

            for item in itertools.chain([] if first is None else [first], items):
                if isinstance(item, bytes):
                    data = item
                elif isinstance(item, str):
                    data = item.encode()
                else:
                    data = _json.dumps(item).encode() + b"\n"
                yield {"kind": "chunk", "data": data}
        except Exception as e:
            # Overload sheds (engine queue full, admission refused) carry
            # an http_status/retry_after so the proxy can answer an
            # honest 503 + Retry-After instead of a bare 500.
            msg = {"kind": "error", "error": f"{type(e).__name__}: {e}"}
            status = getattr(e, "http_status", None)
            if status:
                msg["status"] = status
                msg["retry_after"] = getattr(e, "retry_after", 1)
                msg["reason"] = getattr(e, "reason", "overload")
            yield msg
        finally:
            with self._lock:
                self._ongoing -= 1


def _drive(gen):
    """Yield from a sync or async generator, synchronously."""
    if hasattr(gen, "__anext__"):
        import asyncio

        loop = asyncio.new_event_loop()
        try:
            while True:
                try:
                    yield loop.run_until_complete(gen.__anext__())
                except StopAsyncIteration:
                    break
        finally:
            loop.close()
    else:
        yield from gen
