"""HTTP ingress: a minimal asyncio HTTP/1.1 server actor.

Reference: ``python/ray/serve/_private/proxy.py:754`` (per-node proxy).
The proxy owns a routing table (route prefix → app/ingress deployment,
pushed by the controller via long-poll), assigns each request through the
power-of-two router, and streams the response back. Plain asyncio — no
web framework is needed for the request/response shapes Serve handles.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import urllib.parse
from typing import Any

from ..core import api as ray
from ..observability import tracing
from .long_poll import LongPollClient
from .replica import Request
from .router import (CONTROLLER_NAME, DeadlineExceeded, DeploymentHandle,
                     RequestShed, prefix_group_key)


def _request_deadline_budget(request: Request) -> float:
    """End-to-end deadline budget (seconds) for one request, resolved at
    the front door: the ``x-raytpu-deadline-ms`` header beats a
    ``timeout_s`` JSON body field beats the ``serve_default_deadline_s``
    config. 0 = no deadline (the request may wait forever)."""
    header = request.headers.get("x-raytpu-deadline-ms", "")
    if header:
        try:
            return max(0.0, float(header) / 1000.0)
        except ValueError:
            pass
    if request.body and request.headers.get(
            "content-type", "").startswith("application/json"):
        try:
            body = json.loads(request.body)
            t = body.get("timeout_s")
            if t is not None:
                return max(0.0, float(t))
        except Exception:
            pass
    from ..core.config import get_config

    return max(0.0, get_config().serve_default_deadline_s)


def _request_model_id(request: Request) -> str:
    """Multiplexed model id, unified at the front door: the legacy
    ``serve_multiplexed_model_id`` header, the tenancy spelling
    ``x-raytpu-model``, and an OpenAI-style JSON body ``model`` field
    all resolve to the SAME routing key — a client using any spelling
    lands on the same resident replica (and the same tenant ledger)."""
    from .multiplex import resolve_model_id

    body = None
    if request.body and request.headers.get(
            "content-type", "").startswith("application/json"):
        try:
            body = json.loads(request.body)
        except Exception:
            body = None
    return resolve_model_id(request.headers, body)


def _request_cost_estimate(request: Request) -> float:
    """Estimated token cost for WFQ (prompt length + max_tokens), parsed
    from OpenAI-style JSON bodies at the front door. The ByteTokenizer
    maps ~1 char to 1 token, so character length IS the prompt token
    estimate. Non-JSON / unparseable requests cost 1.0 (plain
    per-request fairness — the pre-cost behavior). The estimate is
    corrected at retire via the tenant's published EWMA ratio."""
    if not (request.body and request.headers.get(
            "content-type", "").startswith("application/json")):
        return 1.0
    try:
        body = json.loads(request.body)
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        if not prompt and isinstance(body.get("messages"), list):
            prompt = "\n".join(
                str(m.get("content", "")) for m in body["messages"]
                if isinstance(m, dict))
        max_tokens = int(body.get("max_tokens", 16))
        return float(max(1, len(str(prompt or "")) + max(0, max_tokens)))
    except Exception:
        return 1.0


def _request_prefix_group(request: Request) -> str:
    """Prefix-group key for affinity routing, extracted at the front
    door: an explicit ``x-raytpu-session`` header (multi-turn sessions)
    beats the hash of the prompt's leading characters (shared system
    prompts) parsed from OpenAI-style JSON bodies; non-LLM requests get
    no key and route by pure load."""
    session = request.headers.get("x-raytpu-session", "")
    if session:
        return prefix_group_key(session_id=session)
    text = ""
    if request.body and request.headers.get(
            "content-type", "").startswith("application/json"):
        try:
            body = json.loads(request.body)
            session = str(body.get("session_id") or "")
            if session:
                return prefix_group_key(session_id=session)
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            if not prompt and isinstance(body.get("messages"), list):
                prompt = "\n".join(
                    str(m.get("content", "")) for m in body["messages"]
                    if isinstance(m, dict))
            text = str(prompt or "")
        except Exception:
            return ""
    elif request.query_params.get("prompt"):
        text = str(request.query_params["prompt"])
    return prefix_group_key(text=text)


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._routes: list[dict] = []  # [{prefix, app, deployment}] longest-prefix-first
        self._handles: dict[tuple[str, str], DeploymentHandle] = {}
        self._ready = threading.Event()
        self._start_error: str | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        controller = ray.get_actor(CONTROLLER_NAME)
        self._long_poll = LongPollClient(controller, {"routes": self._update_routes})
        try:
            snap = ray.get(controller.get_snapshot.remote("routes"), timeout=30)
            if snap:
                self._update_routes(snap)
        except Exception:
            pass
        self._thread = threading.Thread(target=self._serve_forever, daemon=True, name="serve-http")
        self._thread.start()
        self._ready.wait(timeout=30)

    def _update_routes(self, table: Any) -> None:
        table = sorted(table or [], key=lambda e: len(e["prefix"]), reverse=True)
        self._routes = table

    def _serve_forever(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        # Deep executor: router assigns may BLOCK under backpressure; with
        # the default ~5-thread pool a handful of saturated-replica waits
        # would starve every other request's executor hops (deadlock spiral
        # until timeouts clear it).
        from concurrent.futures import ThreadPoolExecutor

        self._loop.set_default_executor(
            ThreadPoolExecutor(max_workers=64, thread_name_prefix="serve-proxy"))

        async def _start():
            server = await asyncio.start_server(self._handle_conn, self._host, self._port)
            self._port = server.sockets[0].getsockname()[1]
            self._ready.set()
            async with server:
                await server.serve_forever()

        try:
            self._loop.run_until_complete(_start())
        except Exception as e:
            # surface bind/listen failures to ready()/address() callers
            # instead of pretending the proxy is up
            self._start_error = f"{type(e).__name__}: {e}"
            self._ready.set()

    def _check_started(self) -> None:
        self._ready.wait(timeout=30)
        if self._start_error is not None:
            raise RuntimeError(f"HTTP proxy failed to start: {self._start_error}")

    def address(self) -> str:
        self._check_started()
        return f"http://{self._host}:{self._port}"

    def ready(self) -> bool:
        self._check_started()
        return True

    def apply_config(self, overrides: dict) -> dict:
        """Live-tune serve knobs (router queue bound, shed policy) in
        THIS proxy process — the router reads config per request, so a
        change takes effect on the next assignment without a proxy
        restart. Returns the previous values so a caller can restore."""
        from ..core.config import get_config

        cfg = get_config()
        prev = {}
        for k, v in (overrides or {}).items():
            if not hasattr(cfg, k):
                raise AttributeError(f"unknown config entry {k!r}")
            prev[k] = getattr(cfg, k)
            setattr(cfg, k, v)
        return prev

    def overload_stats(self) -> dict:
        """Per-deployment overload counters from this proxy's routers
        (sheds by reason, router-queue deadline expiries, circuit
        states) — merged into ``serve.status()`` by the API layer."""
        out: dict = {}
        for (app, dep), handle in list(self._handles.items()):
            router = handle._router_holder.get("router")
            if router is None:
                continue
            out.setdefault(app, {})[dep] = router.overload_snapshot()
        return out

    # ------------------------------------------------------------- http core
    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    break
                streamed = await self._dispatch(request, writer)
                if not streamed:
                    break  # streaming error mid-body: close the connection
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    def _write_full(writer, status: str, body: bytes, content_type: str = "application/json",
                    trace_id: str = "", extra_headers: dict | None = None):
        extra = f"x-raytpu-trace-id: {trace_id}\r\n" if trace_id else ""
        for k, v in (extra_headers or {}).items():
            extra += f"{k}: {v}\r\n"
        writer.write((
            f"HTTP/1.1 {status}\r\nContent-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n{extra}Connection: keep-alive\r\n\r\n"
        ).encode() + body)

    async def _read_request(self, reader: asyncio.StreamReader) -> Request | None:
        try:
            line = await reader.readline()
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, target, _version = line.decode().split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0))
        body = await reader.readexactly(length) if length else b""
        parsed = urllib.parse.urlsplit(target)
        query = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        return Request(method=method, path=parsed.path, query=query, headers=headers, body=body)

    async def _dispatch(self, request: Request, writer) -> bool:
        """Route + drive one request. Every request flows through the
        replica's streaming path (reference proxy.py:754 — ASGI messages
        over a streaming generator task): the first wire message decides
        between a buffered JSON reply and a chunked/SSE streamed body.
        Returns False when the connection must close (error mid-stream)."""
        if request.path == "/-/healthz":
            self._write_full(writer, "200 OK", b'"ok"')
            await writer.drain()
            return True
        # Chaos injection point: ingress drops/delays (http_ingress
        # FaultPlan rules, or "http.ingress=..." in the env spec) — lets
        # fault tests exercise client retry behavior at the front door.
        from ..core.rpc import get_chaos

        chaos = get_chaos()
        drop, delay = False, 0.0
        if hasattr(chaos, "http_ingress_fault"):
            drop, delay = chaos.http_ingress_fault()
        else:
            drop = chaos.should_fail_request("http.ingress", tag="serve")
            delay = chaos.request_delay_s("http.ingress", tag="serve")
        if delay > 0:
            await asyncio.sleep(delay)
        if drop:
            self._write_full(
                writer, "503 Service Unavailable",
                json.dumps({"error": "chaos-injected ingress fault"}).encode())
            await writer.drain()
            return True
        route = next((r for r in self._routes if request.path.startswith(r["prefix"])), None)
        if route is None:
            self._write_full(writer, "404 Not Found",
                             json.dumps({"error": f"no route for {request.path}"}).encode())
            await writer.drain()
            return True
        key = (route["app"], route["deployment"])
        handle = self._handles.get(key)
        if handle is None:
            handle = self._handles[key] = DeploymentHandle(*key)
        # Multiplexing/tenancy: the target model id rides a request
        # header (reference serve_multiplexed_model_id, or the tenancy
        # spelling x-raytpu-model, or the JSON body's model field — one
        # routing key) and biases routing toward replicas with the
        # adapter resident; it also names the request's TENANT for
        # quotas / weighted fair queueing.
        model_id = _request_model_id(request)
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        # Prefix/session affinity: requests sharing a session id or a
        # prompt prefix stick to the replica whose engine already holds
        # their KV (the router spills off an overloaded one).
        group = _request_prefix_group(request)
        if group:
            handle = handle.options(prefix_group=group)
        # End-to-end deadline, stamped HERE (ingress) as an absolute wall
        # clock and threaded router → replica → engine: expiry anywhere
        # downstream fails fast instead of burning capacity.
        budget = _request_deadline_budget(request)
        deadline = time.time() + budget if budget else None
        if deadline is not None:
            handle = handle.options(deadline=deadline)
        # WFQ cost: estimated tokens (prompt length + max_tokens), so
        # router-level fair queueing charges big requests more than
        # small ones instead of a flat 1.0 per request.
        cost = _request_cost_estimate(request)
        if cost != 1.0:
            handle = handle.options(request_cost=cost)
        # Root span for the request (or a continuation of the client's
        # trace via the x-raytpu-trace header); everything downstream —
        # router queue, replica task, engine prefill/decode — chains
        # under this context. The trace id is echoed back in a response
        # header so clients can pull the tree with `cli trace <id>`.
        ctx = tracing.context_from_headers(request.headers)
        t0 = time.time()
        status = "200"

        def _shed_span(reason: str) -> None:
            # One `llm.shed` span per refused request: the trace-store
            # view of overload protection, tagged with WHY it was shed.
            tracing.record_span(tracing.make_span(
                "llm.shed", "serve", t0, time.time(),
                ctx.trace_id, ctx.parent_id, attrs={
                    "reason": reason, "app": route["app"],
                    "deployment": route["deployment"],
                    "tenant": model_id or "default"}))

        def _retry_after_hint() -> int:
            try:
                return handle._get_router().retry_after_hint()
            except Exception:
                return 1

        try:
            loop = asyncio.get_running_loop()
            stream = None
            try:
                # assign + submit off-loop (the router may block on
                # backpressure); bind the trace context across the hop.
                stream = await loop.run_in_executor(
                    None, tracing.bind(ctx, handle.remote_streaming, request))
                head = await stream.__anext__()
            except StopAsyncIteration:
                status = "500"
                self._write_full(writer, "500 Internal Server Error",
                                 json.dumps({"error": "empty response stream"}).encode(),
                                 trace_id=ctx.trace_id)
                await writer.drain()
                return True
            except RequestShed as e:
                # Overload protection refused the request: an honest,
                # FAST 503 with a Retry-After derived from the observed
                # service rate — the client backs off instead of piling
                # onto a collapsing queue.
                status = "503"
                if stream is not None:
                    stream.close()
                _shed_span(e.reason)
                self._write_full(
                    writer, "503 Service Unavailable",
                    json.dumps({"error": str(e), "reason": e.reason}).encode(),
                    trace_id=ctx.trace_id,
                    extra_headers={"Retry-After": e.retry_after})
                await writer.drain()
                return True
            except DeadlineExceeded as e:
                status = "504"
                if stream is not None:
                    stream.close()
                _shed_span("deadline")
                self._write_full(writer, "504 Gateway Timeout",
                                 json.dumps({"error": str(e)}).encode(),
                                 trace_id=ctx.trace_id)
                await writer.drain()
                return True
            except TimeoutError as e:
                status = "503"
                if stream is not None:
                    stream.close()  # release the router slot, cancel the replica
                _shed_span("saturated")
                self._write_full(
                    writer, "503 Service Unavailable",
                    json.dumps({"error": str(e)}).encode(),
                    trace_id=ctx.trace_id,
                    extra_headers={"Retry-After": _retry_after_hint()})
                await writer.drain()
                return True
            except Exception as e:
                from ..core.status import ActorDiedError

                if stream is not None:
                    stream.close()
                if isinstance(e, ActorDiedError):
                    # Replica-death retries exhausted (or death before the
                    # replacement is up): the controller is already
                    # replacing it — tell the client when to come back
                    # instead of a bare 500.
                    status = "503"
                    _shed_span("replica_death")
                    self._write_full(
                        writer, "503 Service Unavailable",
                        json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                        trace_id=ctx.trace_id,
                        extra_headers={"Retry-After": _retry_after_hint()})
                    await writer.drain()
                    return True
                status = "500"
                self._write_full(writer, "500 Internal Server Error",
                                 json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                                 trace_id=ctx.trace_id)
                await writer.drain()
                return True

            if head.get("kind") == "error":
                stream.close()  # settle the router slot
                # Replica-side sheds (engine queue bound) arrive as error
                # messages carrying their own status + Retry-After.
                head_status = head.get("status") or "500 Internal Server Error"
                status = head_status.split()[0]
                extra = None
                if head.get("retry_after") is not None:
                    extra = {"Retry-After": head["retry_after"]}
                    _shed_span(head.get("reason", "overload"))
                self._write_full(writer, head_status,
                                 json.dumps({"error": head["error"]}).encode(),
                                 trace_id=ctx.trace_id, extra_headers=extra)
                await writer.drain()
                return True
            if head.get("kind") == "full":
                stream.close()  # single-message stream: release the slot now
                result = head.get("data")
                body = result if isinstance(result, bytes) else json.dumps(result).encode()
                self._write_full(writer, "200 OK", body, trace_id=ctx.trace_id)
                await writer.drain()
                return True

            return await self._stream_body(request, writer, stream, head, ctx)
        finally:
            tracing.record_span(tracing.make_span(
                f"http {request.method} {request.path}", "serve", t0, time.time(),
                ctx.trace_id, ctx.parent_id, ctx.span_id,
                attrs={"app": route["app"], "deployment": route["deployment"],
                       "status": status}))

    async def _stream_body(self, request: Request, writer, stream, head,
                           ctx) -> bool:
        # Streaming body: chunked transfer encoding, flushed per chunk
        # (SSE works over this: content_type text/event-stream).
        writer.write((
            f"HTTP/1.1 {head.get('status', '200 OK')}\r\n"
            f"Content-Type: {head.get('content_type', 'application/octet-stream')}\r\n"
            f"x-raytpu-trace-id: {ctx.trace_id}\r\n"
            "Transfer-Encoding: chunked\r\nConnection: keep-alive\r\n"
            "Cache-Control: no-cache\r\n\r\n"
        ).encode())
        await writer.drain()
        try:
            async for msg in stream:
                if msg.get("kind") == "error":
                    # Headers already sent: close WITHOUT the chunked
                    # terminator — a spec-compliant client then sees a
                    # truncated (failed) body, not a well-formed success.
                    return False
                data = msg.get("data", b"")
                if data:
                    writer.write(b"%x\r\n" % len(data) + data + b"\r\n")
                    await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
            return True
        except (ConnectionError, asyncio.CancelledError):
            raise  # client went away: finally-close cancels the generator
        except Exception:
            return False
        finally:
            stream.close()  # settle the router slot; cancel if unfinished
