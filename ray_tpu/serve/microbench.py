"""Serve microbenchmark suite: the stack's own overhead, no model.

Equivalent of the reference's Serve microbenchmarks
(``python/ray/serve/_private/benchmarks/`` — handle/HTTP noop latency
and streaming throughput). A no-op deployment isolates what the serving
stack itself costs — handle path (router + replica actor call), HTTP
path (proxy + router + replica), and the streaming generator path — so
the headline LLM serve bench's TTFT can be decomposed into stack time
vs engine time.

Run: ``python -m ray_tpu.serve.microbench`` — prints one JSON line.
PERF.md records the table; VERDICT r3 weak #2 is the requirement.
"""

from __future__ import annotations

import json
import statistics
import threading
import time
import urllib.request


def build_noop_app():
    """The no-op app the suite measures (module-level so tests exercise
    the same deployment ``main()`` runs)."""
    from . import api as serve
    from .deployment import deployment

    @deployment(max_ongoing_requests=64)
    class Noop:
        def __call__(self, request):
            if request.query_params.get("stream"):
                n = int(request.query_params.get("chunks", "100"))

                def gen():
                    yield {"__serve_response__": True,
                           "content_type": "text/event-stream"}
                    for i in range(n):
                        yield f"data: {i}\n\n"
                    yield "data: [DONE]\n\n"

                return gen()
            return "ok"

        def noop(self):
            return "ok"

    return Noop.bind()


def _pcts(samples_ms: list[float]) -> dict:
    s = sorted(samples_ms)
    return {
        "p50_ms": round(statistics.median(s), 2),
        "p95_ms": round(s[max(0, int(len(s) * 0.95) - 1)], 2),
    }


def _latency_then_throughput(fn, *, n_seq: int, n_conc: int,
                             concurrency: int) -> dict:
    """Shared harness: sequential latency percentiles, then threaded
    closed-loop throughput of ``fn`` (one no-op request per call)."""
    lat = []
    for _ in range(n_seq):
        t0 = time.perf_counter()
        fn()
        lat.append(1000 * (time.perf_counter() - t0))

    errors: list[str] = []
    counter = {"n": 0}
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if counter["n"] >= n_conc:
                    return
                counter["n"] += 1
            try:
                fn()
            except Exception as e:
                errors.append(str(e))
                return

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"throughput bench errors: {errors[:3]}")
    return {**_pcts(lat), "rps": round(n_conc / wall, 1),
            "concurrency": concurrency}


def bench_handle_noop(handle, *, n_seq: int = 300, n_conc: int = 300,
                      concurrency: int = 16) -> dict:
    """DeploymentHandle round trip: router slot + replica actor call +
    result transport."""
    def one():
        assert handle.remote().result(timeout=60) == "ok"

    return _latency_then_throughput(
        one, n_seq=n_seq, n_conc=n_conc, concurrency=concurrency)


def bench_http_noop(addr: str, *, n_seq: int = 300, n_conc: int = 300,
                    concurrency: int = 16) -> dict:
    """Full HTTP path: proxy parse + route + handle + chunk back."""
    def one():
        with urllib.request.urlopen(addr + "/", timeout=60) as r:
            assert r.read() == b'"ok"'

    return _latency_then_throughput(
        one, n_seq=n_seq, n_conc=n_conc, concurrency=concurrency)


def bench_streaming(addr: str, *, chunks: int = 2000, runs: int = 3) -> dict:
    """SSE chunk throughput through proxy + streaming-generator path, and
    time-to-first-chunk (the stack's share of streaming TTFT)."""
    rates = []
    ttfc = []
    for _ in range(runs):
        t0 = time.perf_counter()
        n = 0
        first = None
        with urllib.request.urlopen(
                addr + f"/?stream=1&chunks={chunks}", timeout=120) as r:
            for line in r:
                if line.startswith(b"data:"):
                    if first is None:
                        first = time.perf_counter() - t0
                    n += 1
        if first is None:
            raise RuntimeError(
                f"no SSE chunks received from {addr} (non-SSE response?)")
        rates.append(n / (time.perf_counter() - t0))
        ttfc.append(1000 * first)
    return {
        "chunks_per_s": round(statistics.median(rates), 1),
        "first_chunk_ms": round(statistics.median(ttfc), 2),
        "chunks": chunks,
    }


def main() -> dict:
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.run(build_noop_app(), name="microbench", route_prefix="/")
    handle = serve.get_app_handle("microbench").options(method_name="noop")
    addr = serve.http_address()
    # warmup: replica cold start + route table
    handle.remote().result(timeout=60)
    with urllib.request.urlopen(addr + "/", timeout=60) as r:
        r.read()

    out = {
        "handle_noop": bench_handle_noop(handle),
        "http_noop": bench_http_noop(addr),
        "streaming": bench_streaming(addr),
    }
    serve.shutdown()
    ray_tpu.shutdown()
    return out


if __name__ == "__main__":
    print(json.dumps(main()))
